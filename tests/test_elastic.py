"""Elastic resume: survive preemption, reshard onto a new mesh, resume
mid-epoch (doc/elasticity.md).

The centerpiece is the **preemption drill**: a training run on a 4-device
``data`` mesh catches SIGTERM mid-epoch (the real signal path through
``PreemptionGuard``), drains at the next step-save boundary, writes a
requeue verdict — and a second run RESUMES ON A 2-DEVICE MESH, finishing
with parameters matching an uninterrupted control run and zero
replayed/skipped batches (the total optimizer step count and the loss
trajectory both certify it).

Around the drill: template-free resharded restore (the sharding sidecar +
``restore_state(mesh=...)``), composed-mesh coverage matching the
``dryrun_multichip``/pod-recipe surfaces, checkpoint-save retry fault
injection, PreemptionGuard semantics, requeue-verdict classification, and
DataPipeline iterator-state round-trips across world-size changes.
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import dmlcloud_tpu as dml
from dmlcloud_tpu.checkpoint import (
    CheckpointDir,
    read_requeue_verdict,
    write_requeue_verdict,
)
from dmlcloud_tpu.data import DataPipeline
from dmlcloud_tpu.parallel import mesh as mesh_lib
from dmlcloud_tpu.parallel import runtime


def _mesh(n, axes=None):
    return mesh_lib.create_mesh(axes or {"data": n}, devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# respec_for_mesh: the spec re-targeting primitive
# ---------------------------------------------------------------------------

class TestRespecForMesh:
    def test_axis_kept_when_present_and_divisible(self):
        mesh = _mesh(4, {"data": 2, "fsdp": 2})
        assert mesh_lib.respec_for_mesh(P("fsdp", None), (8, 4), mesh) == P("fsdp", None)

    def test_missing_axis_dropped(self):
        mesh = _mesh(2)
        assert mesh_lib.respec_for_mesh(P("fsdp", None), (8, 4), mesh) == P(None, None)

    def test_non_divisible_axis_relocates(self):
        # fsdp=4 no longer divides dim 0 (6) but divides dim 1 (8, >= 2*4)
        mesh = _mesh(4, {"fsdp": 4})
        assert mesh_lib.respec_for_mesh(P("fsdp", None), (6, 8), mesh) == P(None, "fsdp")

    def test_non_divisible_axis_dropped_with_no_home(self):
        mesh = _mesh(4, {"fsdp": 4})
        assert mesh_lib.respec_for_mesh(P("fsdp"), (6,), mesh) == P(None)

    def test_tuple_axes_roundtrip_json(self):
        spec = P(("data", "fsdp"), None, "model")
        back = mesh_lib.spec_from_jsonable(
            json.loads(json.dumps(mesh_lib.spec_to_jsonable(spec)))
        )
        assert back == spec


# ---------------------------------------------------------------------------
# template-free resharded restore (the sharding sidecar)
# ---------------------------------------------------------------------------

def _save_sharded_state(tmp_path, mesh, scope="s"):
    state = {
        "params": {
            "w": jax.device_put(
                jnp.arange(32.0).reshape(8, 4), NamedSharding(mesh, P("fsdp", None))
            ),
            "b": jax.device_put(jnp.ones(4), NamedSharding(mesh, P())),
        },
        "step": jax.device_put(jnp.asarray(7), NamedSharding(mesh, P())),
    }
    ckpt = CheckpointDir(tmp_path / "run")
    if not ckpt.is_valid:
        ckpt.create()
    ckpt.state_manager(scope, async_save=False)
    ckpt.save_state(1, state, scope=scope)
    ckpt.wait_until_finished()
    return ckpt, state


class TestReshardedRestore:
    def test_sidecar_records_mesh_and_specs(self, tmp_path, single_runtime):
        ckpt, _ = _save_sharded_state(tmp_path, _mesh(4, {"data": 2, "fsdp": 2}))
        side = ckpt.read_sharding_sidecar("s", 1)
        assert side["mesh"] == {"data": 2, "fsdp": 2}
        assert side["specs"]["params/w"] == ["fsdp", None]
        assert side["specs"]["params/b"] == []
        ckpt.close()

    def test_restore_onto_smaller_mesh_without_template(self, tmp_path, single_runtime):
        """The tentpole contract: a save taken on an N-device mesh restores
        onto an M-device mesh (N != M) with no caller-built template."""
        ckpt, state = _save_sharded_state(tmp_path, _mesh(4, {"data": 2, "fsdp": 2}))
        mesh2 = _mesh(2)
        restored = ckpt.restore_state(scope="s", mesh=mesh2)
        w = restored["params"]["w"]
        assert w.sharding.mesh.shape == {"data": 2}
        assert w.sharding.spec == P(None, None)  # fsdp axis gone -> replicated
        np.testing.assert_array_equal(np.asarray(w), np.asarray(state["params"]["w"]))
        assert int(restored["step"]) == 7
        ckpt.close()

    def test_restore_onto_larger_mesh(self, tmp_path, single_runtime):
        ckpt, state = _save_sharded_state(tmp_path, _mesh(2, {"fsdp": 2}))
        mesh8 = _mesh(8, {"fsdp": 8})
        restored = ckpt.restore_state(1, scope="s", mesh=mesh8)
        w = restored["params"]["w"]
        assert w.sharding.spec == P("fsdp", None)  # 8 divides dim 0 (8)
        np.testing.assert_array_equal(np.asarray(w), np.asarray(state["params"]["w"]))
        ckpt.close()

    def test_missing_sidecar_degrades_to_policy(self, tmp_path, single_runtime):
        ckpt, state = _save_sharded_state(tmp_path, _mesh(4, {"data": 2, "fsdp": 2}))
        ckpt._sharding_sidecar_file("s", 1).unlink()
        restored = ckpt.restore_state(scope="s", mesh=_mesh(2))
        w = restored["params"]["w"]
        assert w.sharding.spec == P()  # default policy: replicate
        np.testing.assert_array_equal(np.asarray(w), np.asarray(state["params"]["w"]))
        ckpt.close()

    def test_composed_mesh_pod_surface(self, tmp_path, single_runtime):
        """The dryrun_multichip / pod-recipe mesh shape: params laid out by
        T5X-style rules on ('data','fsdp','model'), restored onto a pure
        ('data','fsdp') mesh of half the devices — the model axis folds
        away, values survive bit-exact."""
        from dmlcloud_tpu.models.transformer import llama_partition_rules

        mesh8 = mesh_lib.create_mesh({"data": 2, "fsdp": 2, "model": 2})
        params = {
            "layer": {
                "attention": {"wq": {"kernel": jnp.arange(128.0).reshape(8, 16)}},
                "mlp": {"wi": {"kernel": jnp.arange(64.0).reshape(8, 8)}},
            }
        }
        params = mesh_lib.shard_pytree(params, mesh8, llama_partition_rules())
        ckpt = CheckpointDir(tmp_path / "pod")
        ckpt.create()
        ckpt.state_manager("pod", async_save=False)
        ckpt.save_state(1, {"params": params}, scope="pod")
        ckpt.wait_until_finished()

        mesh4 = _mesh(4, {"data": 2, "fsdp": 2})
        restored = ckpt.restore_state(scope="pod", mesh=mesh4)["params"]
        for a, b in zip(
            jax.tree_util.tree_leaves(restored), jax.tree_util.tree_leaves(params)
        ):
            assert a.sharding.mesh.shape == {"data": 2, "fsdp": 2}
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        ckpt.close()


# ---------------------------------------------------------------------------
# checkpoint-save retry (transient filesystem errors)
# ---------------------------------------------------------------------------

class TestSaveRetry:
    def _ckpt(self, tmp_path):
        ckpt = CheckpointDir(tmp_path / "retry")
        ckpt.create()
        ckpt.state_manager("s", async_save=False)
        ckpt.save_backoff_s = 0.0  # no sleeping in tests
        return ckpt

    def test_transient_failure_retried_then_succeeds(self, tmp_path, single_runtime, caplog):
        ckpt = self._ckpt(tmp_path)
        mgr = ckpt.state_manager("s")
        real_save, calls = mgr.save, []

        def flaky(*a, **k):
            calls.append(1)
            if len(calls) <= 2:
                raise OSError("NFS hiccup")
            return real_save(*a, **k)

        mgr.save = flaky
        with caplog.at_level("WARNING", logger="dmlcloud_tpu"):
            ckpt.save_state(1, {"w": jnp.ones(3)}, scope="s")
        mgr.save = real_save
        ckpt.wait_until_finished()
        assert len(calls) == 3
        assert sum("transient filesystem error" in r.message for r in caplog.records) == 2
        restored = ckpt.restore_state(1, template={"w": jnp.zeros(3)}, scope="s")
        np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones(3))
        ckpt.close()

    def test_persistent_failure_surfaces_original_error(self, tmp_path, single_runtime):
        ckpt = self._ckpt(tmp_path)
        mgr = ckpt.state_manager("s")
        calls = []

        def dead(*a, **k):
            calls.append(1)
            raise OSError(f"still down ({len(calls)})")

        mgr.save = dead
        with pytest.raises(OSError, match="still down \\(1\\)"):
            ckpt.save_state(1, {"w": jnp.ones(3)}, scope="s")
        assert len(calls) == ckpt.save_retries
        ckpt.close()

    def test_non_oserror_not_retried(self, tmp_path, single_runtime):
        ckpt = self._ckpt(tmp_path)
        mgr = ckpt.state_manager("s")
        calls = []

        def broken(*a, **k):
            calls.append(1)
            raise ValueError("not transient")

        mgr.save = broken
        with pytest.raises(ValueError):
            ckpt.save_state(1, {"w": jnp.ones(3)}, scope="s")
        assert len(calls) == 1
        ckpt.close()


# ---------------------------------------------------------------------------
# PreemptionGuard
# ---------------------------------------------------------------------------

class TestPreemptionGuard:
    def test_signal_flips_flag_and_records_name(self):
        guard = runtime.PreemptionGuard(signals=("SIGUSR1",)).install()
        try:
            assert guard.coordinated() is False
            os.kill(os.getpid(), signal.SIGUSR1)
            assert guard.triggered is True
            assert guard.signal_name == "SIGUSR1"
            assert guard.coordinated() is True
        finally:
            guard.uninstall()

    def test_uninstall_restores_disposition_and_disarms(self):
        prev = signal.getsignal(signal.SIGUSR1)
        guard = runtime.PreemptionGuard(signals=("SIGUSR1",)).install()
        guard.uninstall()
        assert signal.getsignal(signal.SIGUSR1) == prev
        guard.triggered = True
        assert guard.coordinated() is False  # disarmed guards never drain

    def test_bad_signal_name_installs_nothing(self):
        prev = signal.getsignal(signal.SIGUSR1)
        with pytest.raises(AttributeError):
            runtime.PreemptionGuard(signals=("SIGUSR1", "SIGNOPE")).install()
        assert signal.getsignal(signal.SIGUSR1) == prev

    def test_default_signals_add_slurm_warning_signal(self, monkeypatch):
        monkeypatch.delenv("SLURM_PROCID", raising=False)
        assert runtime.PreemptionGuard().signals == ("SIGTERM", "SIGINT")
        monkeypatch.setenv("SLURM_PROCID", "0")
        assert runtime.PreemptionGuard().signals == ("SIGTERM", "SIGINT", "SIGUSR1")


# ---------------------------------------------------------------------------
# requeue verdict
# ---------------------------------------------------------------------------

class TestRequeueVerdict:
    def test_roundtrip_and_schema(self, tmp_path):
        write_requeue_verdict(tmp_path, True, "drained on SIGTERM", "preemption", epoch=3)
        v = read_requeue_verdict(tmp_path)
        assert v["v"] == 1 and v["requeue"] is True and v["kind"] == "preemption"
        assert v["epoch"] == 3 and "written_at" in v

    def test_corrupt_verdict_reads_none(self, tmp_path):
        (tmp_path / "requeue.json").write_text("{not json")
        assert read_requeue_verdict(tmp_path) is None

    def test_classification(self):
        p = dml.TrainingPipeline(name="cls")
        assert p._classify_failure(FloatingPointError("nan"))[0] is False
        assert p._classify_failure(KeyboardInterrupt())[0] is False
        assert p._classify_failure(OSError("disk"))[0] is True
        requeue, kind, reason = p._classify_failure(
            runtime.BarrierTimeout("epoch", 60.0, [3])
        )
        assert requeue is True and kind == "hang" and "[3]" in reason
        assert p._classify_failure(RuntimeError("bug"))[0] is False

    def test_watchdog_dump_fires_on_dump_hook(self, tmp_path):
        from dmlcloud_tpu.telemetry.watchdog import HangWatchdog

        seen = []
        wd = HangWatchdog(tmp_path, rank=0, threshold_s=10.0, clock=lambda: 0.0)
        wd.on_dump = seen.append
        wd.dump("no progress for 99s")
        assert seen == ["no progress for 99s"]


# ---------------------------------------------------------------------------
# DataPipeline resumable iterator state
# ---------------------------------------------------------------------------

class TestDataPipelineState:
    def test_cursor_counts_and_roundtrips(self, single_runtime):
        pipe = DataPipeline.from_source(list(range(10)))
        it = iter(pipe)
        assert [next(it) for _ in range(4)] == [0, 1, 2, 3]
        state = pipe.state_dict()
        assert state == {"v": 1, "epoch": None, "global_offset": 4, "world_size": 1}

        fresh = DataPipeline.from_source(list(range(10)))
        fresh.load_state_dict(state)
        assert list(fresh) == [4, 5, 6, 7, 8, 9]
        # the resumed pass's own cursor continues from the skip
        assert fresh.state_dict()["global_offset"] == 10

    def test_shuffle_pack_chain_resumes_exactly(self, single_runtime):
        """The replay fast-forward re-derives reservoir/pack/RNG state: the
        resumed tail is bit-identical to the uninterrupted pass."""

        def build():
            p = DataPipeline.from_source(
                [np.arange(i % 7 + 1, dtype=np.int32) for i in range(40)]
            )
            return p.shuffle(8, seed=3).pack(16).batch(2, collate=lambda b: np.stack([x["tokens"] for x in b]))

        ref = build()
        ref.set_epoch(2)
        full = list(ref)

        cut = 3
        interrupted = build()
        interrupted.set_epoch(2)
        it = iter(interrupted)
        for _ in range(cut):
            next(it)
        state = interrupted.state_dict()
        it.close()

        resumed = build()
        resumed.load_state_dict(state)
        tail = list(resumed)
        assert len(tail) == len(full) - cut
        for a, b in zip(tail, full[cut:]):
            np.testing.assert_array_equal(a, b)

    def test_world_size_change_scales_offset(self, single_runtime, monkeypatch):
        pipe = DataPipeline.from_source(list(range(12)))
        it = iter(pipe)
        for _ in range(3):
            next(it)
        monkeypatch.setattr(runtime, "world_size", lambda: 2)
        state = pipe.state_dict()
        assert state["global_offset"] == 6 and state["world_size"] == 2

        # resume at world size 3: each rank skips 6 // 3 = 2 of ITS elements
        monkeypatch.setattr(runtime, "world_size", lambda: 3)
        fresh = DataPipeline.from_source(list(range(12)))
        fresh.load_state_dict(state)
        assert next(iter(fresh)) == 2

    def test_indivisible_offset_warns_and_rounds_down(self, single_runtime, monkeypatch, caplog):
        pipe = DataPipeline.from_source(list(range(12)))
        state = {"v": 1, "epoch": None, "global_offset": 7, "world_size": 7}
        monkeypatch.setattr(runtime, "world_size", lambda: 2)
        with caplog.at_level("WARNING", logger="dmlcloud_tpu"):
            pipe.load_state_dict(state)
        assert pipe._pending_skip == 3
        assert any("not divisible" in r.message for r in caplog.records)

    def test_bad_state_rejected(self, single_runtime):
        with pytest.raises(ValueError):
            DataPipeline.from_source([1]).load_state_dict({"v": 99})


# ---------------------------------------------------------------------------
# THE PREEMPTION DRILL: SIGTERM mid-epoch on data=4, resume on data=2
# ---------------------------------------------------------------------------

N_BATCHES = 10
SAVE_EVERY = 2
KILL_AFTER = 5  # SIGTERM after batch 5 -> drain at the step-6 save boundary


def _drill_batches():
    rng = np.random.RandomState(0)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    xs = rng.randn(N_BATCHES, 8, 4).astype(np.float32)
    return [{"x": x, "y": x @ w} for x in xs]


class _SigtermAfter:
    """Dataset that delivers a REAL SIGTERM to this process after batch K —
    the production preemption path, signal handler and all."""

    def __init__(self, batches, kill_after=None):
        self._batches = batches
        self._kill_after = kill_after
        self.fired = False

    def __iter__(self):
        for i, b in enumerate(self._batches):
            yield b
            if self._kill_after is not None and not self.fired and i + 1 == self._kill_after:
                self.fired = True
                os.kill(os.getpid(), signal.SIGTERM)

    def __len__(self):
        return len(self._batches)


class _DrillStage(dml.TrainValStage):
    def __init__(self, dataset):
        super().__init__()
        self._dataset = dataset

    def checkpoint_every_steps(self):
        return SAVE_EVERY

    def device_prefetch(self):
        return 0  # keep batch consumption aligned with steps

    def pre_stage(self):
        self.pipeline.register_model(
            "lin",
            apply_fn=lambda p, x: x @ p["w"],
            params={"w": jnp.zeros((4, 1))},
            verbose=False,
        )
        self.pipeline.register_optimizer("sgd", optax.sgd(0.05))
        self.pipeline.register_dataset("train", self._dataset, verbose=False)

    def step(self, state, batch):
        return jnp.mean((state.apply_fn(state.params, batch["x"]) - batch["y"]) ** 2)

    def val_epoch(self):
        pass


def _drill_run(tmp_path, dataset, n_devices, epochs=2, preemptible=False):
    pipe = dml.TrainingPipeline(name="drill")
    pipe.set_mesh(mesh_lib.create_mesh({"data": n_devices}, devices=jax.devices()[:n_devices]))
    pipe.enable_checkpointing(str(tmp_path), resume=True)
    if preemptible:
        pipe.enable_preemption_handling(signals=("SIGTERM",))
    stage = _DrillStage(dataset)
    pipe.append_stage(stage, max_epochs=epochs, name="stage")
    pipe.run()
    pipe.checkpoint_dir.close()
    return pipe, stage


def test_preemption_drill_reshard_and_resume(tmp_path, single_runtime):
    """The acceptance drill: SIGTERM mid-epoch on a 4-device mesh; resume on
    a 2-device mesh; loss trajectory, metric continuity, and exact
    data-order resumption (0 replayed / 0 skipped batches)."""
    # control: never interrupted, on the SMALL mesh (the mesh the resumed
    # run finishes on) — the gold trajectory
    _, control = _drill_run(tmp_path / "control", _SigtermAfter(_drill_batches()), 2)
    want = np.asarray(control.state.params["w"])
    control_losses = [float(v) for v in control.tracker["train/loss"]]

    # phase A: preempted mid-epoch on data=4
    ds = _SigtermAfter(_drill_batches(), kill_after=KILL_AFTER)
    pipe1, stage1 = _drill_run(tmp_path / "run", ds, 4, preemptible=True)
    assert stage1._mid_epoch_exit and stage1._preempt_exit
    assert int(stage1.state.step) == 6  # drained exactly at the save boundary
    assert int(stage1.state.params["w"].sharding.mesh.devices.size) == 4

    # the drain left a machine-readable requeue verdict with the save latency
    verdict = read_requeue_verdict(pipe1.checkpoint_dir.path)
    assert verdict["requeue"] is True and verdict["kind"] == "preemption"
    assert "SIGTERM" in verdict["reason"]
    assert verdict["mid_epoch"] is True and verdict["epoch"] == 1
    assert verdict["save_on_preempt_latency_s"] > 0

    # the step save carries a sharding sidecar for the 4-device mesh
    side = pipe1.checkpoint_dir.read_sharding_sidecar("stage.steps", 6)
    assert side["mesh"] == {"data": 4}

    # phase B: the requeue — SAME run dir, HALF the devices
    pipe2, stage2 = _drill_run(pipe1.checkpoint_dir.path, _SigtermAfter(_drill_batches()), 2)
    # exact data-order resumption: 2 epochs x 10 batches, not one step more
    # or less — a replayed or skipped batch cannot produce step == 20
    assert int(stage2.state.step) == 2 * N_BATCHES
    assert stage2.current_epoch == 3
    assert int(stage2.state.params["w"].sharding.mesh.devices.size) == 2

    # loss trajectory: same computation as the uninterrupted control (only
    # collective reduction order differs between the meshes)
    got = np.asarray(stage2.state.params["w"])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    resumed_losses = [float(v) for v in stage2.tracker["train/loss"]]
    assert len(resumed_losses) == 2  # metric continuity: both epochs recorded
    # epoch 2 saw identical data from identical params on both runs
    np.testing.assert_allclose(resumed_losses[1], control_losses[1], rtol=1e-5)
    # the completed requeue verdict stands down
    v2 = read_requeue_verdict(pipe2.checkpoint_dir.path)
    assert v2["requeue"] is False and v2["kind"] == "completed"


def test_drill_with_resumable_datapipeline(tmp_path, single_runtime):
    """Same drill with a DataPipeline train dataset: the step-save sidecar
    carries the iterator state and the resume fast-forwards through
    ``load_state_dict`` instead of the raw batch skip."""
    batches = _drill_batches()

    class _PipelineSigterm(_SigtermAfter):
        pass

    def make_ds(kill_after=None):
        return DataPipeline.from_source(_PipelineSigterm(batches, kill_after))

    _, control = _drill_run(tmp_path / "control", make_ds(), 2)
    want = np.asarray(control.state.params["w"])

    pipe1, stage1 = _drill_run(tmp_path / "run", make_ds(kill_after=KILL_AFTER), 4, preemptible=True)
    assert int(stage1.state.step) == 6
    meta = json.loads(
        (pipe1.checkpoint_dir.path / "meta" / "stage.steps" / "6.json").read_text()
    )
    assert meta["world_size"] == 1
    assert meta["data"] == {"v": 1, "epoch": 1, "global_offset": 6, "world_size": 1}

    pipe2, stage2 = _drill_run(pipe1.checkpoint_dir.path, make_ds(), 2)
    assert int(stage2.state.step) == 2 * N_BATCHES
    np.testing.assert_allclose(np.asarray(stage2.state.params["w"]), want, rtol=1e-5, atol=1e-6)


def test_nan_failure_writes_no_requeue_verdict(tmp_path, single_runtime):
    """A deterministic failure (non-finite loss) must NOT ask for a requeue
    — it would recur forever."""

    class NaNStage(_DrillStage):
        def log_every(self):
            return 1

        def step(self, state, batch):
            return jnp.mean(batch["x"]) * jnp.float32(np.nan)

    pipe = dml.TrainingPipeline(name="nan")
    pipe.enable_checkpointing(str(tmp_path), resume=True)
    stage = NaNStage(_drill_batches())
    pipe.append_stage(stage, max_epochs=1, name="stage")
    with pytest.raises(FloatingPointError):
        pipe.run()
    verdict = read_requeue_verdict(pipe.checkpoint_dir.path)
    assert verdict["requeue"] is False and verdict["kind"] == "exception"
    pipe.checkpoint_dir.close()


# ---------------------------------------------------------------------------
# MixPipeline: the elastic contract (world-size scaling + the drill)
# ---------------------------------------------------------------------------

class TestMixElasticContract:
    def _mk(self):
        return DataPipeline.mix(
            [
                DataPipeline.from_source(list(range(100, 130))),
                DataPipeline.from_source(list(range(200, 220))),
            ],
            weights=[3, 1],
            seed=5,
        )

    def test_world_size_change_scales_mix_cursor(self, single_runtime, monkeypatch):
        """Save under world size 4, resume under 2: the element offset, the
        draw counter, and every CHILD cursor are stored globally and
        re-derived per-rank — the lock that makes a reshard resume land on
        the exact next sample instead of replaying or skipping."""
        m = self._mk()
        it = iter(m)
        consumed = [next(it) for _ in range(3)]
        from_a = sum(1 for x in consumed if x < 200)
        monkeypatch.setattr(runtime, "world_size", lambda: 4)
        state = m.state_dict()
        assert state["kind"] == "mix" and state["world_size"] == 4
        assert state["global_offset"] == 12 and state["global_draws"] == 12
        assert state["children"][0]["global_offset"] == from_a * 4
        assert state["children"][1]["global_offset"] == (3 - from_a) * 4

        monkeypatch.setattr(runtime, "world_size", lambda: 2)
        fresh = self._mk()
        fresh.load_state_dict(state)
        # per-rank cursors under the NEW world size: global / 2
        assert fresh._mix_resume == {
            "consumed": 6,
            "draws": 6,
            "exhausted": [False, False],
        }
        assert fresh._sources[0]._pending_skip == from_a * 2
        assert fresh._sources[1]._pending_skip == (3 - from_a) * 2
        # no replay through the mix itself: children fast-forward themselves
        assert fresh._pending_skip == 0
        # and after one element the resumed cursor continues globally
        next(iter(fresh))
        assert fresh.state_dict()["global_offset"] == 12 + 2

    def test_indivisible_mix_cursor_warns_and_rounds_down(self, single_runtime, monkeypatch, caplog):
        m = self._mk()
        it = iter(m)
        for _ in range(3):
            next(it)
        monkeypatch.setattr(runtime, "world_size", lambda: 4)
        state = m.state_dict()  # 12 global
        monkeypatch.setattr(runtime, "world_size", lambda: 5)
        fresh = self._mk()
        with caplog.at_level("WARNING", logger="dmlcloud_tpu"):
            fresh.load_state_dict(state)
        assert fresh._mix_resume["consumed"] == 2  # 12 // 5
        assert any("not divisible" in r.message for r in caplog.records)

    def test_drill_with_mix_datapipeline(self, tmp_path, single_runtime):
        """The preemption drill fed by a weighted mix: SIGTERM mid-epoch,
        drain at the save boundary, resume on a smaller mesh — the step-save
        sidecar carries the MIX state (kind 'mix', child cursors included)
        and the resumed trajectory matches the uninterrupted control with 0
        replayed or skipped samples."""
        batches = _drill_batches()

        def make_ds(kill_after=None):
            first = _SigtermAfter(batches[:5], kill_after)
            return DataPipeline.mix(
                [DataPipeline.from_source(first), DataPipeline.from_source(batches[5:])],
                weights=[2, 1],
                seed=3,
            )

        _, control = _drill_run(tmp_path / "control", make_ds(), 2)
        want = np.asarray(control.state.params["w"])
        assert int(control.state.step) == 2 * N_BATCHES

        pipe1, stage1 = _drill_run(tmp_path / "run", make_ds(kill_after=3), 4, preemptible=True)
        assert stage1._mid_epoch_exit
        drained = int(stage1.state.step)
        assert 0 < drained < N_BATCHES and drained % SAVE_EVERY == 0
        meta = json.loads(
            (pipe1.checkpoint_dir.path / "meta" / "stage.steps" / f"{drained}.json").read_text()
        )
        assert meta["data"]["kind"] == "mix"
        assert meta["data"]["global_offset"] == drained
        assert len(meta["data"]["children"]) == 2

        pipe2, stage2 = _drill_run(pipe1.checkpoint_dir.path, make_ds(), 2)
        # exact resumption: 2 epochs x 10 mixed batches, not one step more
        # or less — a replayed or skipped sample cannot produce step == 20
        assert int(stage2.state.step) == 2 * N_BATCHES
        np.testing.assert_allclose(
            np.asarray(stage2.state.params["w"]), want, rtol=1e-5, atol=1e-6
        )


class _BatchShardReader:
    """Drill dataset over ON-DISK shards: a ShardReader whose records are
    batch indices, mapped to the drill's real batches at yield time — so
    the registered dataset IS the shard reader (the sidecar saves ITS
    ``kind='shards'`` cursor), while the step still sees dict batches.
    Optionally delivers a real SIGTERM after batch K (the _SigtermAfter
    pattern)."""

    def __new__(cls, corpus_dir, batches, kill_after=None):
        from dmlcloud_tpu.data import ShardReader

        class _Reader(ShardReader):
            def _shard_iter(self, epoch):
                for i, rec in enumerate(super()._shard_iter(epoch)):
                    yield batches[int(rec[0])]
                    if kill_after is not None and not getattr(self, "_fired", False) and i + 1 == kill_after:
                        self._fired = True
                        os.kill(os.getpid(), signal.SIGTERM)

        return _Reader(corpus_dir, read_ahead=4)


class TestShardElasticContract:
    def _corpus(self, tmp_path, n=40):
        from dmlcloud_tpu.data import build_corpus

        d = tmp_path / "corpus"
        docs = [np.full(3, i, np.int32) for i in range(n)]
        build_corpus(d, docs, shard_tokens=9)  # 3 records/shard -> many shards
        return str(d), docs

    def test_world_size_change_scales_shard_cursor(self, tmp_path, single_runtime, monkeypatch):
        """Save under world size 4, resume under 2: the shard cursor is a
        global record offset plus its (shard_id, record_offset) disk
        location, and the resume SEEKS — no pending replay skip."""
        from dmlcloud_tpu.data import ShardReader

        d, docs = self._corpus(tmp_path)
        monkeypatch.setattr(runtime, "world_size", lambda: 4)
        reader = ShardReader(d)
        it = iter(reader)
        consumed = [next(it) for _ in range(3)]
        assert all(np.array_equal(a, docs[i * 4]) for i, a in enumerate(consumed))
        state = reader.state_dict()
        it.close()
        assert state["kind"] == "shards" and state["world_size"] == 4
        assert state["global_offset"] == 12
        # record 12 sits in shard 4 at offset 0 (3 records per shard)
        assert (state["shard_id"], state["record_offset"]) == (4, 0)

        monkeypatch.setattr(runtime, "world_size", lambda: 2)
        fresh = ShardReader(d)
        fresh.load_state_dict(state)
        # per-rank cursor under the NEW world size: global / 2, via seek
        assert fresh._shard_resume == 6
        assert fresh._pending_skip == 0
        it = iter(fresh)
        assert np.array_equal(next(it), docs[12])  # rank 0: g = 0 + 6*2
        # and the resumed cursor continues globally
        assert fresh.state_dict()["global_offset"] == 12 + 2
        it.close()

    def test_indivisible_shard_cursor_warns_and_rounds_down(self, tmp_path, single_runtime, monkeypatch, caplog):
        from dmlcloud_tpu.data import ShardReader

        d, _ = self._corpus(tmp_path)
        monkeypatch.setattr(runtime, "world_size", lambda: 4)
        reader = ShardReader(d)
        it = iter(reader)
        for _ in range(3):
            next(it)
        state = reader.state_dict()  # 12 global
        it.close()
        monkeypatch.setattr(runtime, "world_size", lambda: 5)
        fresh = ShardReader(d)
        with caplog.at_level("WARNING", logger="dmlcloud_tpu"):
            fresh.load_state_dict(state)
        assert fresh._shard_resume == 2  # 12 // 5
        assert any("not divisible" in r.message for r in caplog.records)

    def test_drill_with_shard_reader(self, tmp_path, single_runtime):
        """The preemption drill fed from DISK: batches come through a
        ShardReader over a multi-shard corpus, SIGTERM lands mid-epoch,
        the run drains at the save boundary with the 'shards' cursor in
        the sidecar, and the resume on a smaller mesh finishes with
        parameters matching the uninterrupted control — 0 replayed or
        skipped samples, resumed by SEEK instead of replay."""
        batches = _drill_batches()
        d, _ = self._corpus(tmp_path, n=N_BATCHES)  # record i -> batch i

        _, control = _drill_run(tmp_path / "control", _BatchShardReader(d, batches), 2)
        want = np.asarray(control.state.params["w"])
        assert int(control.state.step) == 2 * N_BATCHES

        pipe1, stage1 = _drill_run(
            tmp_path / "run", _BatchShardReader(d, batches, kill_after=3), 4, preemptible=True
        )
        assert stage1._mid_epoch_exit
        drained = int(stage1.state.step)
        assert 0 < drained < N_BATCHES and drained % SAVE_EVERY == 0
        meta = json.loads(
            (pipe1.checkpoint_dir.path / "meta" / "stage.steps" / f"{drained}.json").read_text()
        )
        assert meta["data"]["kind"] == "shards"
        assert meta["data"]["global_offset"] == drained
        # the sidecar names the disk location the resume will seek to
        assert (meta["data"]["shard_id"], meta["data"]["record_offset"]) == divmod(drained, 3)

        pipe2, stage2 = _drill_run(pipe1.checkpoint_dir.path, _BatchShardReader(d, batches), 2)
        # exact resumption: 2 epochs x 10 disk batches, not one step more
        # or less — a replayed or skipped record cannot produce step == 20
        assert int(stage2.state.step) == 2 * N_BATCHES
        np.testing.assert_allclose(
            np.asarray(stage2.state.params["w"]), want, rtol=1e-5, atol=1e-6
        )
