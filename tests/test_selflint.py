"""Self-lint gate (tier-1): the framework and its examples must satisfy the
very contract the linter enforces — zero findings over ``dmlcloud_tpu/``
and ``examples/``.

This is the CI tripwire the lint subsystem exists for: a future Stage
subclass, example, or hot-loop edit that reintroduces a host sync, an
undonated train step, or a retrace hazard fails HERE, on CPU, at review
time — not three PRs later on a chip. Legitimate exceptions carry a
``# dmllint: disable=...`` with a justification (see stage.py's eager
bisection path for the canonical one).
"""

from pathlib import Path

import dmlcloud_tpu
from dmlcloud_tpu.lint import lint_paths

PACKAGE_DIR = Path(dmlcloud_tpu.__file__).resolve().parent
REPO_ROOT = PACKAGE_DIR.parent


def _report(findings):
    return "\n".join(f.format() for f in findings)


def test_package_lints_clean():
    findings = lint_paths([PACKAGE_DIR])
    assert findings == [], (
        f"dmlcloud_tpu/ violates its own sync-point contract:\n{_report(findings)}\n"
        "Fix the hazard or suppress it with '# dmllint: disable=ID -- why'."
    )


def test_examples_lint_clean():
    examples = REPO_ROOT / "examples"
    if not examples.is_dir():  # installed-package runs have no examples tree
        import pytest

        pytest.skip("examples/ not present next to the package")
    findings = lint_paths([examples])
    assert findings == [], (
        f"examples/ violate the sync-point contract:\n{_report(findings)}\n"
        "Examples are copied verbatim by users — they must model the contract."
    )
