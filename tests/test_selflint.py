"""Self-lint gate (tier-1): the framework, its examples, the bench harness,
and the scripts must satisfy the very contracts the linter enforces — zero
findings over ``dmlcloud_tpu/``, ``examples/``, ``bench.py``, ``scripts/``,
with ALL rule families enabled (sync-point DML1xx, sharding DML2xx,
concurrency DML3xx).

This is the CI tripwire the lint subsystem exists for: a future Stage
subclass, example, or hot-loop edit that reintroduces a host sync, an
undonated train step, a typo'd mesh axis, or a half-locked thread protocol
fails HERE, on CPU, at review time — not three PRs later on a chip.
Legitimate exceptions carry a ``# dmllint: disable=...`` with a
justification (see stage.py's eager bisection path for the canonical one).
``scripts/lint_gate.sh`` runs the same scan as a GitHub-annotating CI step.
"""

from pathlib import Path

import pytest

import dmlcloud_tpu
from dmlcloud_tpu.lint import lint_paths

PACKAGE_DIR = Path(dmlcloud_tpu.__file__).resolve().parent
REPO_ROOT = PACKAGE_DIR.parent


def _report(findings):
    return "\n".join(f.format() for f in findings)


def test_package_lints_clean():
    findings = lint_paths([PACKAGE_DIR])
    assert findings == [], (
        f"dmlcloud_tpu/ violates its own sync-point contract:\n{_report(findings)}\n"
        "Fix the hazard or suppress it with '# dmllint: disable=ID -- why'."
    )


def test_examples_lint_clean():
    examples = REPO_ROOT / "examples"
    if not examples.is_dir():  # installed-package runs have no examples tree
        pytest.skip("examples/ not present next to the package")
    findings = lint_paths([examples])
    assert findings == [], (
        f"examples/ violate the sync-point contract:\n{_report(findings)}\n"
        "Examples are copied verbatim by users — they must model the contract."
    )


def test_examples_and_bench_configs_verify_clean():
    """Self-VERIFY gate (PR 20): the IR-level pass over every example and
    bench-child config that registers a ``dml_verify_programs()`` hook —
    the programs users copy and the programs the perf receipts time must
    clear the DML6xx contracts (donation effective in the compiled
    artifact, no baked-in host callbacks, axes resolving, budgets met).
    Any justified suppression carries a rationale comment at its anchor."""
    from dmlcloud_tpu.lint.ir import verify_paths

    targets = [p for p in (REPO_ROOT / "examples", REPO_ROOT / "scripts") if p.exists()]
    if not targets:  # installed-package runs carry neither
        pytest.skip("examples/ and scripts/ not present next to the package")
    stats: dict = {}
    findings = verify_paths(targets, stats=stats)
    assert findings == [], (
        f"examples/scripts programs violate the IR-verify contract:\n{_report(findings)}\n"
        "Fix the program or suppress with '# dmllint: disable=ID -- why'."
    )
    # the lock is meaningful only while hooks exist and programs trace
    assert stats["programs"] >= 3


def test_bench_and_scripts_lint_clean():
    """bench.py and scripts/ produce the numbers the perf claims rest on —
    a dishonest timing loop or a donated-buffer read THERE corrupts the
    receipts, so they sit under the same gate as the framework."""
    targets = [p for p in (REPO_ROOT / "bench.py", REPO_ROOT / "scripts") if p.exists()]
    if not targets:  # installed-package runs carry neither
        pytest.skip("bench.py / scripts/ not present next to the package")
    findings = lint_paths(targets)
    assert findings == [], (
        f"bench.py / scripts/ violate the lint contract:\n{_report(findings)}\n"
        "Fix the hazard or suppress it with '# dmllint: disable=ID -- why'."
    )
