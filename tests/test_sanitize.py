"""Runtime sanitizer (lint/sanitize.py): TrainingPipeline(sanitize=...)
must catch an injected implicit device-to-host transfer inside the step
loop — raising in "error" mode, logging + journaling + continuing in
"warn" mode, and doing literally nothing in the default "off" mode. The
framework's own accounted sync points (StallTimer spans) stay sanctioned.
"""

import json
import logging
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import dmlcloud_tpu as dml
from dmlcloud_tpu.lint import Sanitizer, SanitizerError
from dmlcloud_tpu.lint.sanitize import RULE_D2H, RULE_H2D, RULE_NONFINITE, sanctioned


class _SanStage(dml.TrainValStage):
    """Linear-regression toy; subclasses inject violations."""

    def pre_stage(self):
        rng = np.random.RandomState(3)
        xs = rng.randn(4, 16, 4).astype(np.float32)
        batches = [{"x": x, "y": x.sum(axis=-1, keepdims=True)} for x in xs]
        self.pipeline.register_model(
            "linear",
            apply_fn=lambda p, x: x @ p["w"],
            params={"w": jnp.zeros((4, 1))},
            verbose=False,
        )
        self.pipeline.register_optimizer("sgd", optax.sgd(0.05))
        self.pipeline.register_dataset("train", batches, verbose=False)

    def step(self, state, batch):
        pred = state.apply_fn(state.params, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    def val_epoch(self):
        pass


class _LeakyStage(_SanStage):
    """Injects one implicit D2H conversion per step — the DML101 hazard,
    now caught at runtime."""

    def train_epoch(self):
        for batch in self._feed(self.train_dataset()):
            self.state, metrics = self._train_step_fn(self.state, batch)
            np.asarray(metrics["loss"])  # the injected implicit transfer


def _pipeline(stage, mode, max_epochs=1, **kw):
    p = dml.TrainingPipeline(sanitize=mode, **kw)
    p.append_stage(stage, max_epochs=max_epochs, name="SanStage")
    return p


class TestModes:
    def test_error_mode_raises_on_injected_d2h(self, single_runtime):
        p = _pipeline(_LeakyStage(), "error")
        with pytest.raises(SanitizerError) as exc:
            p.run()
        assert exc.value.findings and exc.value.findings[0].rule == RULE_D2H
        assert "test_sanitize" in exc.value.findings[0].path

    def test_warn_mode_logs_and_continues(self, single_runtime, caplog, tmp_path):
        p = _pipeline(_LeakyStage(), "warn", max_epochs=2, telemetry=str(tmp_path))
        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu"):
            p.run()  # completes despite the per-step violation
        assert any(RULE_D2H in r.getMessage() for r in caplog.records)
        # one finding per SITE, not per step/epoch
        assert [f.rule for f in p.sanitizer_findings] == [RULE_D2H]
        # the violation rides the telemetry journal as a 'sanitizer' span
        records = [
            json.loads(line)
            for line in (tmp_path / "journal-rank0.jsonl").read_text().splitlines()
        ]
        spans = [r for r in records if r["kind"] == "sanitizer"]
        assert spans and spans[0]["rule"] == RULE_D2H
        assert spans[0]["line"] > 0

    def test_off_mode_changes_nothing(self, single_runtime):
        p = _pipeline(_LeakyStage(), None)
        p.run()
        assert p.sanitizer_findings == []

    def test_clean_stage_passes_error_mode(self, single_runtime):
        """The framework's own sync points (StallTimer fetch at log
        boundaries, the epoch-end block) are sanctioned — a contract-clean
        stage must run under sanitize="error" without a single finding."""
        stage = _SanStage()
        p = _pipeline(stage, "error", max_epochs=2)
        p.run()
        assert p.sanitizer_findings == []

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError):
            dml.TrainingPipeline(sanitize="maybe")
        with pytest.raises(ValueError):
            Sanitizer("loud")


class TestNanArm:
    def test_error_mode_arms_debug_nans(self, single_runtime):
        class NanStage(_SanStage):
            def nan_guard(self):
                return False  # isolate the sanitizer's debug_nans arm

            def step(self, state, batch):
                pred = state.apply_fn(state.params, batch["x"])
                return jnp.log(-jnp.abs(pred.mean()) - 1.0)  # always NaN

        p = _pipeline(NanStage(), "error")
        with pytest.raises(FloatingPointError):
            p.run()
        assert [f.rule for f in p.sanitizer_findings] == [RULE_NONFINITE]
        # the window restored the global flag
        assert not jax.config.jax_debug_nans

    def test_off_mode_does_not_arm_debug_nans(self, single_runtime):
        class NanStage(_SanStage):
            def nan_guard(self):
                return False

            def log_every(self):
                return 0

            def step(self, state, batch):
                pred = state.apply_fn(state.params, batch["x"])
                return jnp.log(-jnp.abs(pred.mean()) - 1.0)

        p = _pipeline(NanStage(), None)
        p.run()  # NaNs flow silently — exactly the default behavior
        assert p.sanitizer_findings == []


class TestDispatchProbe:
    def test_host_numpy_leaves_flagged(self):
        san = Sanitizer("error")
        wrapped = san.wrap_dispatch(lambda b: b, where="test.step")
        with pytest.raises(SanitizerError) as exc:
            wrapped({"x": np.ones(4, np.float32)})
        assert exc.value.findings[0].rule == RULE_H2D

    def test_device_leaves_pass(self):
        san = Sanitizer("warn")
        wrapped = san.wrap_dispatch(lambda b: b, where="test.step")
        wrapped({"x": jnp.ones(4)})
        assert san.findings == []

    def test_off_returns_fn_unchanged(self):
        san = Sanitizer("off")
        fn = lambda b: b  # noqa: E731
        assert san.wrap_dispatch(fn) is fn


def _sharded_value(mesh):
    """A replicated multi-device array — the shape every pipeline metric
    has (the probe's interception point; single-device CPU arrays alias
    host memory and convert zero-copy, see lint/sanitize.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(jnp.ones(8).sum(), NamedSharding(mesh, P()))


class TestSanctioned:
    def test_sanctioned_block_suppresses_probe(self, mesh8):
        """np.asarray inside a StallTimer measure()/fetch() is the
        accounted idiom — the probe must not fire there."""
        from dmlcloud_tpu.utils.profiling import StallTimer

        san = Sanitizer("error")
        value = _sharded_value(mesh8)
        timer = StallTimer()
        with san.epoch_guard(stage="t"):
            timer.fetch(value)  # sanctioned: no raise
            with sanctioned():
                np.asarray(value)  # explicitly sanctioned: no raise
        assert san.findings == []

    def test_probe_fires_outside_sanction(self, mesh8):
        san = Sanitizer("error")
        value = _sharded_value(mesh8)
        with pytest.raises(SanitizerError):
            with san.epoch_guard(stage="t"):
                np.asarray(value)

    def test_probe_inactive_outside_guard(self, mesh8):
        san = Sanitizer("error")
        value = _sharded_value(mesh8)
        np.asarray(value)  # no guard window: plain conversion
        assert san.findings == []
