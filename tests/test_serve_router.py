"""Multi-replica serving front door (dmlcloud_tpu/serve/router.py).

The load-bearing contracts, each tested here:

- routing: N in-process engine replicas behind one submit/step surface;
  placement spreads by least-outstanding load, per-tenant DRR preserves
  FIFO within a tenant, prefix affinity (stable content addresses) sends
  a warm template back to the replica that served it last;
- health: the failure detector runs off ONE injectable ``clock=`` — a
  missed heartbeat fails the replica over with a fake clock, no sleeps;
- failover, at-most-once: live requests on a dead/raising replica are
  re-placed from scratch with bounded retries + exponential backoff and
  end terminal ``error`` when the budget is spent; a retry that lands on
  an engine that secretly admitted the original re-attaches through
  ``DuplicateRequest`` instead of double-admitting; router-wide, every
  request ends in exactly one ``TERMINAL_STATUSES`` state;
- circuit breaker: K consecutive failures trip it open (placements shed
  to siblings), cooldown -> half-open risks ONE probe, only an ``ok``
  probe closes it, a failed probe doubles the cooldown;
- drain: queued requests migrate off (fresh token — the old one stays
  burned), running ones finish in place, the emptied replica is removed
  and a PR-7 ``requeue.json`` verdict records the drain;
- chaos: random replica kills/stalls/drains at every phase under a TIGHT
  pool — per step every replica still audits free+unique-live==capacity,
  no request is ever live on two engines at once, and greedy survivors
  stay token-identical to a fault-free reference engine;
- determinism across interpreters: prefix-cache content addresses and a
  seeded chaos drill's event log are byte-identical under different
  ``PYTHONHASHSEED`` (subprocess test — the hints replicas would exchange
  and the replay log must not depend on per-process hash salt);
- the ledger's per-tenant TTFT percentiles survive record eviction, and
  ``ServeEngine.submit(token=)`` enforces caller idempotency.

The stub-engine tests exercise the router's control plane (pure host
logic) without compiling anything; the integration tests reuse the
tiny-model idiom of tests/test_serve.py.
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

from dmlcloud_tpu.checkpoint import read_requeue_verdict
from dmlcloud_tpu.serve import (
    ChaosMonkey,
    DuplicateRequest,
    Router,
    ServeEngine,
    ServeLedger,
    TERMINAL_STATUSES,
)
from dmlcloud_tpu.serve.prefix_cache import content_key, prefix_keys, root_key
from dmlcloud_tpu.telemetry import journal as journal_mod
from dmlcloud_tpu.telemetry.journal import SpanJournal


# ---------------------------------------------------------------------------
# a fake clock and a pure-host stub engine (the router only sees the
# engine SURFACE: submit/step/status/cancel/output/idle + pool geometry)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


class _StubPool:
    def __init__(self, block_size=4, num_blocks=64):
        self.block_size = block_size
        self.num_blocks = num_blocks

    def blocks_for(self, tokens):
        return max(1, -(-int(tokens) // self.block_size))

    def assert_consistent(self):
        pass


class _StubEngine:
    """In-memory stand-in honouring the engine surface the Router uses.
    ``steps_to_finish`` is the service time in steps, ``max_slots`` caps
    concurrently-running requests (the rest report ``queued``), and
    ``fail_next`` makes the next N ``step()`` calls raise."""

    def __init__(self, *, clock=None, steps_to_finish=2, max_slots=4,
                 block_size=4, num_blocks=64, prefill_chunk=8):
        self.pool = _StubPool(block_size, num_blocks)
        self.draft_pool = None
        self.scheduler = types.SimpleNamespace(prefill_chunk=prefill_chunk)
        self.ledger = ServeLedger()
        self.clock = clock if clock is not None else _Clock()
        self.steps_to_finish = steps_to_finish
        self.max_slots = max_slots
        self.fail_next = 0
        self._all = {}
        self._tokens = {}
        self._next = 0
        self.submits = []  # (rid, token, tenant) admission audit trail

    def submit(self, prompt, max_new_tokens=32, *, token=None, tenant=None, **kw):
        if token is not None and token in self._tokens:
            raise DuplicateRequest(token, self._tokens[token])
        rid = self._next
        self._next += 1
        self._all[rid] = {
            "status": None, "left": self.steps_to_finish, "token": token,
            "prompt": np.asarray(prompt, np.int32), "max_new": int(max_new_tokens),
        }
        if token is not None:
            self._tokens[token] = rid
        self.ledger.arrived(rid, self.clock(), tenant=tenant)
        self.submits.append((rid, token, tenant))
        return rid

    def _running(self):
        live = [r for r, s in self._all.items() if s["status"] is None]
        return live[: self.max_slots]

    def step(self):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError("injected step failure")
        running = self._running()
        now = self.clock()
        for rid in running:
            s = self._all[rid]
            if "first" not in s:
                s["first"] = True
                self.ledger.first_token(rid, now)
            s["left"] -= 1
            if s["left"] <= 0:
                s["status"] = "ok"
                self.ledger.finished(rid, now, "ok")
        return bool(running)

    def status(self, rid):
        if rid not in self._all:
            raise KeyError(rid)
        s = self._all[rid]
        if s["status"] is not None:
            return s["status"]
        return "running" if rid in self._running() else "queued"

    def statuses(self):
        return {rid: self.status(rid) for rid in self._all}

    def cancel(self, rid):
        s = self._all.get(rid)
        if s is None or s["status"] is not None:
            return False
        s["status"] = "cancelled"
        self.ledger.finished(rid, self.clock(), "cancelled")
        return True

    def output(self, rid):
        s = self._all[rid]
        if s["status"] != "ok":
            raise KeyError(rid)
        return np.concatenate([s["prompt"], np.arange(s["max_new"], dtype=np.int32)])

    @property
    def idle(self):
        return all(s["status"] is not None for s in self._all.values())

    def leaked_blocks(self):
        return 0


def _stub_router(n=2, clock=None, engine_kw=None, **router_kw):
    clock = clock if clock is not None else _Clock()
    engines = [_StubEngine(clock=clock, **(engine_kw or {})) for _ in range(n)]
    router_kw.setdefault("drr_quantum", 100)  # placement on first visit
    router_kw.setdefault("backoff_base_s", 0.0)
    return Router(engines, clock=clock, **router_kw), clock


# ---------------------------------------------------------------------------
# routing basics (stub engines — control plane only)
# ---------------------------------------------------------------------------


class TestRouterBasics:
    def test_routes_all_terminal_ok(self):
        router, _ = _stub_router(n=3)
        rids = [
            router.submit(list(range(4)), 4, tenant="a" if i % 2 else "b")
            for i in range(6)
        ]
        outs = router.run(max_steps=50)
        assert router.idle
        assert set(router.statuses().values()) == {"ok"}
        assert router.summary()["statuses"] == {"ok": 6}
        assert router.leaked_blocks() == 0
        for rid in rids:
            assert np.array_equal(outs[rid], router.output(rid))

    def test_least_outstanding_spreads_load(self):
        router, _ = _stub_router(n=2, engine_kw={"steps_to_finish": 10})
        # distinct prompts: identical ones would share an affinity key and
        # deliberately co-locate
        a = router.submit(list(range(4)), 4)
        b = router.submit(list(range(10, 14)), 4)
        router.step()
        assert router._records[a].replica == "r0"
        assert router._records[b].replica == "r1"

    def test_status_lifecycle_and_queued_cancel(self):
        # a tiny quantum: the head needs more credit than one visit grants,
        # so the request stays router-queued across the first steps
        router, _ = _stub_router(n=1, drr_quantum=1)
        rid = router.submit(list(range(16)), 16)
        assert router.status(rid) == "queued"
        assert router.cancel(rid)
        assert router.status(rid) == "cancelled"
        assert not router.cancel(rid)  # already terminal: idempotent no
        assert router.idle
        router.step()  # the cancelled record never places
        assert router._records[rid].replica is None

    def test_unknown_rid_raises(self):
        router, _ = _stub_router(n=1)
        with pytest.raises(KeyError):
            router.status(99)

    def test_per_tenant_fifo_survives_interleaving(self):
        # one slow replica, interleaved tenants, a quantum small enough
        # that placement takes several DRR visits — per-tenant first
        # placements must still come out in arrival order
        router, _ = _stub_router(
            n=2, drr_quantum=2, engine_kw={"steps_to_finish": 1, "max_slots": 1}
        )
        placements = []
        orig = router._place

        def spy(rec, rep, now):
            placements.append((rec.tenant, rec.rid, rec.retries))
            return orig(rec, rep, now)

        router._place = spy
        rids = []
        for i in range(8):
            tenant = "hot" if i % 2 == 0 else "cold"
            rids.append(router.submit(list(range(8)), 8, tenant=tenant))
        router.run(max_steps=200)
        assert router.idle and set(router.statuses().values()) == {"ok"}
        for tenant in ("hot", "cold"):
            first = [rid for (t, rid, retries) in placements
                     if t == tenant and retries == 0]
            assert first == sorted(first), f"tenant {tenant} placed out of order"


# ---------------------------------------------------------------------------
# health detection + failover (fake clock — no sleeps)
# ---------------------------------------------------------------------------


class TestFailover:
    def test_missed_heartbeat_fails_over(self):
        router, clock = _stub_router(
            n=2, heartbeat_timeout_s=1.0, engine_kw={"steps_to_finish": 5}
        )
        rid = router.submit(list(range(4)), 4)
        router.step()
        rec = router._records[rid]
        assert rec.replica == "r0"
        # r0 wedges: it misses steps while the clock runs past the deadline
        router.stall_replica("r0", 10)
        clock.advance(2.0)
        assert router.healthy()["r0"] is False
        router.step()  # r1 beats (it stepped), r0 misses its deadline
        assert router.failovers == 1
        assert rec.replica == "r1" and rec.retries == 1
        assert rec.token.endswith(".f1")  # definitively cancelled: fresh token
        router.run(max_steps=50)
        assert router.status(rid) == "ok"

    def test_step_raise_retries_exhausted_to_error(self):
        router, _ = _stub_router(
            n=2, max_retries=1, breaker_threshold=100,
            engine_kw={"steps_to_finish": 5},
        )
        for rep in router.replicas.values():
            rep.engine.fail_next = 100  # every step raises, everywhere
        rid = router.submit(list(range(4)), 4)
        for _ in range(10):
            router.step()
            if router.idle:
                break
        assert router.status(rid) == "error"
        assert router.idle
        assert router._records[rid].retries == router.max_retries + 1
        with pytest.raises(KeyError):
            router.output(rid)

    def test_kill_reaps_engine_and_keeps_token(self):
        router, _ = _stub_router(n=2, engine_kw={"steps_to_finish": 6})
        a = router.submit(list(range(4)), 4)
        b = router.submit(list(range(4)), 4)
        router.step()
        rec = router._records[a]
        assert rec.replica == "r0"
        token_before = rec.token
        router.kill_replica("r0", "drill")
        r0 = router.replicas["r0"]
        assert not r0.alive and router.kills == 1
        # the reap: nothing left live on the dead engine, audit still clean
        assert all(st in TERMINAL_STATUSES for st in r0.engine.statuses().values())
        # fatal failover keeps the token: if the "dead" replica ever saw
        # the retry, dedup would re-attach (at-most-once) — so no rotation
        assert rec.token == token_before and rec.retries == 1
        router.run(max_steps=60)
        assert router.status(a) == "ok" and router.status(b) == "ok"
        assert router._records[a].replica == "r1"
        assert router.leaked_blocks() == 0

    def test_duplicate_request_reattaches(self):
        router, clock = _stub_router(n=1)
        rid = router.submit(list(range(4)), 4)
        router.step()
        rec = router._records[rid]
        rep = router.replicas[rec.replica]
        erid = rec.engine_rid
        admissions = len(rep.engine.submits)
        # the ambiguous-failure window: the router re-places a request the
        # engine ALREADY admitted under the same token — the engine raises
        # DuplicateRequest and the router re-attaches, never double-admits
        router._place(rec, rep, clock())
        assert rec.engine_rid == erid
        assert len(rep.engine.submits) == admissions


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def _router(self):
        return _stub_router(
            n=2, breaker_threshold=2, breaker_cooldown_s=1.0,
            heartbeat_timeout_s=1e9, max_retries=10,
            engine_kw={"steps_to_finish": 10},
        )

    def test_trip_half_open_probe_close(self):
        router, clock = self._router()
        r0 = router.replicas["r0"]
        r0.engine.fail_next = 2
        router.step()
        assert r0.consec_failures == 1 and r0.breaker == "closed"
        router.step()
        assert r0.breaker == "open"
        # open: placements shed to the sibling (distinct prompts — same
        # ones would share affinity keys and skew the choice)
        a = router.submit(list(range(4)), 4)
        b = router.submit(list(range(10, 14)), 4)
        router.step()
        assert router._records[a].replica == "r1"
        assert router._records[b].replica == "r1"
        # cooldown over: half-open risks exactly ONE probe
        clock.advance(1.5)
        c = router.submit(list(range(20, 24)), 4)
        d = router.submit(list(range(30, 34)), 4)
        router.step()
        assert r0.breaker == "half_open"
        assert router._records[c].replica == "r0" and r0.probe_rid == c
        assert router._records[d].replica == "r1"
        # the probe terminates ok -> the breaker closes
        router.run(max_steps=60)
        assert router.status(c) == "ok"
        assert r0.breaker == "closed" and r0.consec_failures == 0
        assert r0.probe_rid is None

    def test_failed_probe_reopens_with_doubled_cooldown(self):
        router, clock = self._router()
        r0 = router.replicas["r0"]
        r0.engine.fail_next = 2
        router.step()
        router.step()
        assert r0.breaker == "open"
        cooldown = r0.cooldown
        clock.advance(1.5)
        c = router.submit(list(range(20, 24)), 4)
        router.step()
        assert r0.breaker == "half_open" and r0.probe_rid == c
        r0.engine.fail_next = 1  # the probe's very next step fails
        router.step()
        assert r0.breaker == "open"
        assert r0.cooldown == cooldown * 2.0  # back off harder
        assert r0.probe_rid is None
        # the probe request itself failed over to the sibling
        assert router._records[c].replica == "r1"
        router.run(max_steps=60)
        assert router.status(c) == "ok"


# ---------------------------------------------------------------------------
# drain + affinity
# ---------------------------------------------------------------------------


class TestDrainAndAffinity:
    def test_drain_migrates_queued_finishes_running_writes_verdict(self, tmp_path):
        router, _ = _stub_router(
            n=2, run_dir=tmp_path,
            engine_kw={"steps_to_finish": 4, "max_slots": 1},
        )
        a = router.submit(list(range(4)), 4)
        b = router.submit(list(range(10, 14)), 4)
        c = router.submit(list(range(20, 24)), 4)
        router.step()
        # a->r0, b->r1 (least outstanding), c->r0 (tie break) but r0 has
        # one slot: c sits engine-queued — exactly what a drain migrates
        rec_c = router._records[c]
        assert rec_c.replica == "r0"
        assert router.status(c) == "queued"
        token_c = rec_c.token
        router.drain_replica("r0")
        r0 = router.replicas["r0"]
        assert r0.draining and r0.migrated == 1
        assert rec_c.replica is None
        assert rec_c.token == f"{token_c}.m"  # fresh token, old one burned
        assert rec_c.retries == 0  # a migration is not a failure retry
        router.run(max_steps=100)
        assert set(router.statuses().values()) == {"ok"}
        assert router._records[c].replica == "r1"
        assert r0.removed and not r0.alive
        assert router.failovers == 0
        verdict = read_requeue_verdict(tmp_path)
        assert verdict is not None and verdict["requeue"] is False
        assert verdict["kind"] == "completed"
        assert verdict["serve"]["replica"] == "r0"
        assert verdict["serve"]["migrated"] == 1
        assert verdict["serve"]["drained_clean"] is True

    def test_prefix_affinity_beats_load_tiebreak(self):
        router, _ = _stub_router(n=2, engine_kw={"steps_to_finish": 1})
        warm = list(range(8))  # two full blocks: a real affinity key
        a = router.submit(warm, 4)
        router.run(max_steps=20)
        assert router._records[a].replica == "r0"
        # load up r0 so least-outstanding would now prefer r1...
        for rep in router.replicas.values():
            rep.engine.steps_to_finish = 50
        router.submit(list(range(100, 104)), 4)
        b = router.submit(warm, 4)
        router.step()
        # ...but the warm template still routes to the replica that
        # served it last
        assert router._records[b].replica == "r0"

    def test_affinity_falls_back_when_warm_replica_unplaceable(self):
        router, _ = _stub_router(n=2, engine_kw={"steps_to_finish": 1})
        warm = list(range(8))
        a = router.submit(warm, 4)
        router.run(max_steps=20)
        assert router._records[a].replica == "r0"
        router.kill_replica("r0", "gone")
        b = router.submit(warm, 4)
        router.run(max_steps=20)
        assert router.status(b) == "ok"
        assert router._records[b].replica == "r1"


# ---------------------------------------------------------------------------
# telemetry: the router's span kinds
# ---------------------------------------------------------------------------


class TestRouterTelemetry:
    def test_route_failover_drain_spans(self, tmp_path):
        j = SpanJournal(tmp_path / "telemetry", rank=0, ring_size=64)
        journal_mod.activate(j)
        try:
            router, _ = _stub_router(n=2, engine_kw={"steps_to_finish": 3})
            router.submit(list(range(4)), 4)
            router.submit(list(range(4)), 4)
            router.step()
            router.kill_replica("r0", "drill")
            router.run(max_steps=50)
            router.drain_replica("r1")
            router.step()
            assert router.replicas["r1"].removed
        finally:
            journal_mod.deactivate()
        kinds = {r["kind"] for r in j.tail(64)}
        assert {"route", "failover", "replica_drain"} <= kinds


# ---------------------------------------------------------------------------
# request-scoped tracing across failure (PR 19)
# ---------------------------------------------------------------------------


class TestTracePropagation:
    def test_failover_rotates_token_but_keeps_trace(self, tmp_path):
        """A heartbeat failover definitively cancels and resubmits under a
        FRESH token (at-most-once), but the trace id never rotates — the
        retry's spans land in the SAME causal trace as the original
        placement."""
        from dmlcloud_tpu.telemetry.journal import linked_trace_report

        j = SpanJournal(tmp_path / "telemetry", rank=0, ring_size=256)
        journal_mod.activate(j)
        try:
            router, clock = _stub_router(
                n=2, heartbeat_timeout_s=1.0, engine_kw={"steps_to_finish": 5}
            )
            rid = router.submit(list(range(4)), 4)
            router.step()
            rec = router._records[rid]
            trace_before = rec.trace
            router.stall_replica(rec.replica, 10)
            clock.advance(2.0)
            router.step()
            assert rec.retries == 1 and rec.token.endswith(".f1")
            assert rec.trace == trace_before == f"tr-{rid}"
            router.run(max_steps=50)
            assert router.status(rid) == "ok"
        finally:
            journal_mod.deactivate()
        report = linked_trace_report(j.tail(256))
        assert report["orphans"] == []
        spans = report["traces"][f"tr-{rid}"]
        kinds = [r["kind"] for r in spans]
        # original placement, the failover, and the re-placement all link
        assert kinds.count("route") == 2 and kinds.count("failover") == 1

    def test_kill_one_drain_one_drill_has_zero_orphans(self, tmp_path):
        """The router drill's journal walk: kill a replica mid-flight,
        drain another — every request-scoped span still carries its trace
        id (zero orphans) and every submitted request resolves to exactly
        one trace."""
        from dmlcloud_tpu.telemetry.journal import linked_trace_report

        j = SpanJournal(tmp_path / "telemetry", rank=0, ring_size=512)
        journal_mod.activate(j)
        try:
            router, _ = _stub_router(n=3, engine_kw={"steps_to_finish": 4})
            rids = [router.submit(list(range(i, i + 4)), 4) for i in range(6)]
            router.step()
            router.kill_replica("r0", "drill")
            router.run(max_steps=30)
            router.drain_replica("r1", "drill")
            router.run(max_steps=60)
            assert all(router.status(r) in TERMINAL_STATUSES for r in rids)
        finally:
            journal_mod.deactivate()
        report = linked_trace_report(j.tail(512))
        assert report["orphans"] == []
        assert set(report["traces"]) == {f"tr-{r}" for r in rids}
        for spans in report["traces"].values():
            assert spans  # no empty trace

    def test_exhausted_retries_stamp_the_trace_status(self, tmp_path):
        """A request that burns its whole retry budget ends ``error`` AND
        its trace says so: the terminal fault span carries the trace id,
        so ``linked_trace_report`` surfaces the status per trace."""
        from dmlcloud_tpu.telemetry.journal import linked_trace_report

        j = SpanJournal(tmp_path / "telemetry", rank=0, ring_size=256)
        journal_mod.activate(j)
        try:
            router, _ = _stub_router(
                n=2, max_retries=1, breaker_threshold=100,
                engine_kw={"steps_to_finish": 5},
            )
            for rep in router.replicas.values():
                rep.engine.fail_next = 100
            rid = router.submit(list(range(4)), 4)
            for _ in range(10):
                router.step()
                if router.idle:
                    break
            assert router.status(rid) == "error"
        finally:
            journal_mod.deactivate()
        report = linked_trace_report(j.tail(256))
        assert report["orphans"] == []
        assert report["statuses"][f"tr-{rid}"] == "error"


# ---------------------------------------------------------------------------
# ledger: per-tenant percentiles survive eviction (satellite)
# ---------------------------------------------------------------------------


class TestLedgerTenantPercentiles:
    def test_percentiles_survive_record_eviction(self):
        led = ServeLedger(max_records=4)
        for i in range(20):
            tenant = "hot" if i % 2 == 0 else "cold"
            led.arrived(i, float(i), tenant=tenant)
            led.first_token(i, float(i) + (0.1 if tenant == "hot" else 0.5))
            led.finished(i, float(i) + 1.0, "ok")
        assert len(led.records) <= 4  # eviction really happened
        tt = led.summary()["tenant_ttft"]
        assert set(tt) == {"hot", "cold"}
        assert tt["hot"]["n"] == 10 and tt["cold"]["n"] == 10
        assert tt["hot"]["p50_s"] == pytest.approx(0.1)
        assert tt["cold"]["p50_s"] == pytest.approx(0.5)
        assert tt["cold"]["p99_s"] == pytest.approx(0.5)
        # the per-record accessor honestly reads only what is retained
        assert len(led.ttfts("hot")) <= 4

    def test_unknown_tenant_absent(self):
        led = ServeLedger()
        led.arrived(0, 0.0)  # no tenant
        led.first_token(0, 0.5)
        led.finished(0, 1.0, "ok")
        assert led.summary()["tenant_ttft"] == {}


# ---------------------------------------------------------------------------
# engine submit idempotency (satellite; host-side — no decode needed)
# ---------------------------------------------------------------------------


# tiny_model (the shared 61-vocab serve LM) comes from conftest.py,
# session-scoped: the same instance test_serve uses.


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 61, size=(n,)).astype(np.int32)


def _engine(model, params, **kw):
    kw.setdefault("num_blocks", 64)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_slots", 2)
    kw.setdefault("prefill_chunk", 8)
    return ServeEngine(model, params, **kw)


class TestSubmitIdempotency:
    def test_duplicate_token_rejected_with_original_rid(self, tiny_model):
        eng = _engine(*tiny_model)
        rid = eng.submit(_prompt(6), 4, token="job-1")
        with pytest.raises(DuplicateRequest) as exc:
            eng.submit(_prompt(8, seed=1), 4, token="job-1")
        assert exc.value.rid == rid and exc.value.token == "job-1"
        assert eng.submit(_prompt(8, seed=1), 4, token="job-2") != rid

    def test_token_stays_burned_until_record_evicted(self, tiny_model):
        eng = _engine(*tiny_model, max_done=2)
        rids = [eng.submit(_prompt(6, seed=i), 4, token=f"t{i}") for i in range(3)]
        eng.run()
        # t0's record was retention-evicted (max_done=2) — gone from the
        # status surface, and its token is free again; t2's record is
        # retained: still a duplicate
        with pytest.raises(KeyError):
            eng.status(rids[0])
        assert all(eng.status(r) == "ok" for r in rids[1:])
        eng.submit(_prompt(6, seed=0), 4, token="t0")
        with pytest.raises(DuplicateRequest):
            eng.submit(_prompt(6, seed=2), 4, token="t2")


# ---------------------------------------------------------------------------
# the failover property drill: random kills/stalls/drains at every phase
# under a tight pool (real engines — the pool audit is the point)
# ---------------------------------------------------------------------------


class _DrillChaos:
    """Seeded replica-level chaos: at any router step a standing replica
    may be killed, drained, or stalled — guarded so at least one
    non-draining replica always remains."""

    def __init__(self, router, seed):
        self.router = router
        self.rng = np.random.RandomState(seed)
        self.events = []

    def __call__(self, point, seqs):
        r = self.router
        standing = [
            name for name, rep in r.replicas.items()
            if rep.alive and not rep.removed and not rep.draining
        ]
        if len(standing) > 1 and self.rng.random_sample() < 0.02:
            name = standing[int(self.rng.randint(len(standing)))]
            self.events.append(("kill", name))
            r.kill_replica(name, "drill")
            standing.remove(name)
        if len(standing) > 1 and self.rng.random_sample() < 0.02:
            name = standing[int(self.rng.randint(len(standing)))]
            self.events.append(("drain", name))
            r.drain_replica(name)
            standing.remove(name)
        if standing and self.rng.random_sample() < 0.05:
            name = standing[int(self.rng.randint(len(standing)))]
            self.events.append(("stall", name))
            r.stall_replica(name, 2)


class TestFailoverProperty:
    @pytest.mark.slow  # random replica-chaos property drill; the seeded kill+drain integration lock stays tier-1
    def test_random_replica_chaos_under_tight_pool(self, tiny_model, tmp_path):
        model, params = tiny_model
        n_req = 10
        prompts = [_prompt(6 + (i % 3) * 4, seed=100 + i) for i in range(n_req)]
        max_new = [4 + (i % 2) * 2 for i in range(n_req)]
        # the fault-free reference arm: greedy engine output is
        # batch-composition-independent, so one engine serving everything
        # pins the expected tokens for every request
        ref = _engine(model, params)
        ref_rids = [ref.submit(p, m) for p, m in zip(prompts, max_new)]
        ref_outs = ref.run()
        assert all(ref.status(r) == "ok" for r in ref_rids)

        engines = [
            _engine(model, params, num_blocks=24, max_slots=2) for _ in range(3)
        ]
        router = Router(
            engines, heartbeat_timeout_s=1e9, max_retries=3,
            backoff_base_s=0.0, breaker_threshold=3, breaker_cooldown_s=0.01,
            run_dir=tmp_path,
        )
        chaos = _DrillChaos(router, seed=7)
        router.fault_injector = chaos
        placements = []
        orig = router._place

        def spy(rec, rep, now):
            placements.append((rec.tenant, rec.rid, rec.retries))
            return orig(rec, rep, now)

        router._place = spy
        rids = [
            router.submit(p, m, tenant="hot" if i % 2 == 0 else "cold")
            for i, (p, m) in enumerate(zip(prompts, max_new))
        ]
        steps = 0
        while not router.idle and steps < 2000:
            router.step()
            steps += 1
            # the per-step invariants, on EVERY replica, at every phase:
            # free + unique-live == capacity ...
            for rep in router.replicas.values():
                rep.engine.pool.assert_consistent()
            # ... and no request is ever live on two engines at once
            # (at-most-once across failover/migration token rotations)
            live_on = {}
            for name, rep in router.replicas.items():
                for seq in rep.engine._all.values():
                    if seq.status is None and seq.token:
                        base = seq.token.split(".")[0]
                        live_on.setdefault(base, []).append(name)
            for base, names in live_on.items():
                assert len(names) == 1, f"{base} live on {names} at step {steps}"

        assert router.idle, f"drill did not converge (events: {chaos.events})"
        statuses = router.statuses()
        assert set(statuses.values()) <= set(TERMINAL_STATUSES)
        assert router.leaked_blocks() == 0
        # survivors stay token-identical to the fault-free reference
        ok = [rid for rid in rids if statuses[rid] == "ok"]
        assert len(ok) >= n_req // 2, f"too much collateral: {statuses}"
        for rid in ok:
            assert np.array_equal(router.output(rid), ref_outs[rid]), rid
        # strict per-tenant FIFO for first placements
        for tenant in ("hot", "cold"):
            first = [rid for (t, rid, retries) in placements
                     if t == tenant and retries == 0]
            assert first == sorted(first)
        # any drain that ran to completion left its verdict behind
        if any(rep.removed for rep in router.replicas.values()):
            verdict = read_requeue_verdict(tmp_path)
            assert verdict is not None and verdict["serve"]["drained_clean"]


# ---------------------------------------------------------------------------
# token identity through an operator kill + drain (integration)
# ---------------------------------------------------------------------------


class TestRouterIntegration:
    def test_outputs_identical_through_kill_and_drain(self, tiny_model, tmp_path):
        model, params = tiny_model
        prompts = [_prompt(8, seed=200 + i) for i in range(6)]
        ref = _engine(model, params)
        for p in prompts:
            ref.submit(p, 6)
        ref_outs = ref.run()

        engines = [_engine(model, params) for _ in range(3)]
        router = Router(
            engines, heartbeat_timeout_s=1e9, max_retries=2,
            backoff_base_s=0.0, run_dir=tmp_path,
        )
        rids = [router.submit(p, 6, tenant="t") for p in prompts]
        # let work spread, then kill one replica and drain another
        for _ in range(3):
            router.step()
        router.kill_replica("r2", "drill")
        router.drain_replica("r1")
        router.run(max_steps=500)
        assert router.idle
        assert set(router.statuses().values()) == {"ok"}
        assert router.leaked_blocks() == 0
        for i, rid in enumerate(rids):
            assert np.array_equal(router.output(rid), ref_outs[i])
        assert router.replicas["r1"].removed
        assert read_requeue_verdict(tmp_path)["serve"]["replica"] == "r1"


# ---------------------------------------------------------------------------
# cross-process determinism (satellites): stable prefix addresses and a
# byte-identical chaos replay under different PYTHONHASHSEED
# ---------------------------------------------------------------------------

_DET_SCRIPT = r"""
import json
from dmlcloud_tpu.serve.prefix_cache import content_key, prefix_keys, root_key
from dmlcloud_tpu.serve import ChaosMonkey, Router, ServeLedger

out = {"prefix": {
    "keys": prefix_keys(list(range(40)), 8),
    "adapter3": prefix_keys(list(range(40)), 8, adapter=3),
    "root": root_key(0),
    "chain": content_key(123, (7, 8, 9)),
}}


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Pool:
    block_size = 4
    num_blocks = 64

    def blocks_for(self, n):
        return max(1, -(-int(n) // 4))

    def assert_consistent(self):
        pass


class _Stub:
    def __init__(self, clock):
        import types
        self.pool = _Pool()
        self.draft_pool = None
        self.scheduler = types.SimpleNamespace(prefill_chunk=8)
        self.ledger = ServeLedger()
        self.clock = clock
        self._all = {}
        self._next = 0

    def submit(self, prompt, max_new_tokens=8, *, token=None, tenant=None, **kw):
        rid = self._next
        self._next += 1
        self._all[rid] = {"st": None, "left": 3}
        self.ledger.arrived(rid, self.clock(), tenant=tenant)
        return rid

    def step(self):
        did = False
        for rid, s in self._all.items():
            if s["st"] is None:
                did = True
                s["left"] -= 1
                if s["left"] <= 0:
                    s["st"] = "ok"
                    self.ledger.finished(rid, self.clock(), "ok")
        return did

    def status(self, rid):
        if rid not in self._all:
            raise KeyError(rid)
        st = self._all[rid]["st"]
        return st if st is not None else "running"

    def statuses(self):
        return {r: self.status(r) for r in self._all}

    def cancel(self, rid):
        s = self._all.get(rid)
        if s is None or s["st"] is not None:
            return False
        s["st"] = "cancelled"
        self.ledger.finished(rid, self.clock(), "cancelled")
        return True

    @property
    def idle(self):
        return all(s["st"] is not None for s in self._all.values())

    def leaked_blocks(self):
        return 0


clock = _Clock()
router = Router(
    [_Stub(clock) for _ in range(3)], clock=clock,
    heartbeat_timeout_s=1e9, max_retries=3, backoff_base_s=0.0,
    drr_quantum=100,
)
monkey = ChaosMonkey(
    seed=11, p_replica_kill=0.04, max_replica_kills=1,
    p_replica_stall=0.15, replica_stall_steps=2,
).attach_router(router)
for i in range(8):
    router.submit(list(range(i, i + 8)), 8, tenant="a" if i % 2 else "b")
steps = 0
while not router.idle and steps < 300:
    router.step()
    clock.t += 0.01
    steps += 1
out["chaos"] = {
    "log": monkey.log,
    "statuses": {str(k): v for k, v in sorted(router.statuses().items())},
    "failovers": router.failovers,
    "kills": router.kills,
    "idle": router.idle,
}
print(json.dumps(out, sort_keys=True))
"""


@pytest.fixture(scope="module")
def _det_runs():
    """The same seeded drill in two fresh interpreters with DIFFERENT
    hash seeds; both stdouts, raw."""
    outs = []
    for hash_seed in ("0", "4271"):
        env = dict(os.environ, PYTHONHASHSEED=hash_seed, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", _DET_SCRIPT],
            capture_output=True, timeout=240, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr.decode()
        outs.append(proc.stdout)
    return outs


class TestCrossProcessDeterminism:
    def test_prefix_keys_independent_of_hash_seed(self, _det_runs):
        a, b = (json.loads(o)["prefix"] for o in _det_runs)
        assert a == b
        # and both agree with THIS process (a third hash seed, in effect)
        assert a["keys"] == prefix_keys(list(range(40)), 8)
        assert a["adapter3"] == prefix_keys(list(range(40)), 8, adapter=3)
        assert a["root"] == root_key(0)
        assert a["chain"] == content_key(123, (7, 8, 9))
        # adapter id is part of the address: no cross-tenant aliasing
        assert a["keys"] != a["adapter3"]

    def test_chaos_event_log_replays_byte_identical(self, _det_runs):
        a, b = _det_runs
        assert a == b  # the WHOLE drill record, byte for byte
        chaos = json.loads(a)["chaos"]
        assert chaos["idle"] is True
        assert set(chaos["statuses"].values()) <= set(TERMINAL_STATUSES)
        # the drill actually injected something worth replaying
        assert any(kind in ("replica_kill", "replica_stall")
                   for (_, kind, _detail) in chaos["log"])
