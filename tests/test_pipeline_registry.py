"""Registry lookup semantics: optimizer-to-model binding must be
unambiguous (mirroring _model_entry's error), and val_epoch may only
swallow the missing-registration sentinel — never a user ValueError."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import dmlcloud_tpu as dml
from dmlcloud_tpu.stage import DatasetNotFoundError


def _register_model(pipeline, name):
    pipeline.register_model(
        name,
        apply_fn=lambda p, x: x @ p["w"],
        params={"w": jnp.zeros((4, 1))},
        verbose=False,
    )


@pytest.fixture
def pipeline(single_runtime):
    return dml.TrainingPipeline(name="registry")


class TestOptimizerBinding:
    def test_single_unbound_optimizer_serves_any_model(self, pipeline):
        _register_model(pipeline, "a")
        _register_model(pipeline, "b")
        opt = optax.sgd(0.1)
        pipeline.register_optimizer("sgd", opt)
        assert pipeline._optimizer_for("a") is opt
        assert pipeline._optimizer_for("b") is opt

    def test_explicit_binding_wins(self, pipeline):
        _register_model(pipeline, "a")
        _register_model(pipeline, "b")
        opt_a, opt_b = optax.sgd(0.1), optax.adam(1e-3)
        pipeline.register_optimizer("sgd", opt_a, model="a")
        pipeline.register_optimizer("adam", opt_b, model="b")
        assert pipeline._optimizer_for("a") is opt_a
        assert pipeline._optimizer_for("b") is opt_b

    def test_ambiguous_unbound_optimizers_raise(self, pipeline):
        """Two models + two unbound optimizers: the old code silently bound
        the FIRST optimizer to both models."""
        _register_model(pipeline, "a")
        _register_model(pipeline, "b")
        pipeline.register_optimizer("sgd", optax.sgd(0.1))
        pipeline.register_optimizer("adam", optax.adam(1e-3))
        with pytest.raises(ValueError, match="Multiple unbound optimizers"):
            pipeline._optimizer_for("a")

    def test_one_bound_one_unbound_is_unambiguous(self, pipeline):
        _register_model(pipeline, "a")
        _register_model(pipeline, "b")
        opt_a, opt_rest = optax.sgd(0.1), optax.adam(1e-3)
        pipeline.register_optimizer("sgd", opt_a, model="a")
        pipeline.register_optimizer("adam", opt_rest)
        assert pipeline._optimizer_for("a") is opt_a
        assert pipeline._optimizer_for("b") is opt_rest

    def test_two_bound_to_same_model_raise(self, pipeline):
        _register_model(pipeline, "a")
        pipeline.register_optimizer("sgd", optax.sgd(0.1), model="a")
        pipeline.register_optimizer("adam", optax.adam(1e-3), model="a")
        with pytest.raises(ValueError, match="Multiple optimizers"):
            pipeline._optimizer_for("a")

    def test_no_optimizer_raises(self, pipeline):
        _register_model(pipeline, "a")
        with pytest.raises(ValueError, match="No optimizer registered"):
            pipeline._optimizer_for("a")

    def test_bound_elsewhere_only_raises(self, pipeline):
        _register_model(pipeline, "a")
        _register_model(pipeline, "b")
        pipeline.register_optimizer("sgd", optax.sgd(0.1), model="a")
        with pytest.raises(ValueError, match="No optimizer registered for model 'b'"):
            pipeline._optimizer_for("b")

    def test_single_model_multiple_unbound_keeps_first(self, pipeline):
        """One model with several unbound optimizers stays on the historical
        first-wins behavior (no real ambiguity about WHICH model trains)."""
        _register_model(pipeline, "a")
        opt1 = optax.sgd(0.1)
        pipeline.register_optimizer("sgd", opt1)
        pipeline.register_optimizer("adam", optax.adam(1e-3))
        assert pipeline._optimizer_for("a") is opt1

    def test_end_to_end_two_models_two_optimizers(self, single_runtime):
        """Behavior test through a real run: the ambiguity error must surface
        from make_state, not train silently with the wrong optimizer."""

        class AmbiguousStage(dml.TrainValStage):
            def model_name(self):
                return "a"

            def pre_stage(self):
                _register_model(self.pipeline, "a")
                _register_model(self.pipeline, "b")
                self.pipeline.register_optimizer("sgd", optax.sgd(0.1))
                self.pipeline.register_optimizer("adam", optax.adam(1e-3))
                rng = np.random.RandomState(0)
                x = rng.randn(8, 4).astype(np.float32)
                self.pipeline.register_dataset(
                    "train", [{"x": x, "y": x @ rng.randn(4, 1).astype(np.float32)}], verbose=False
                )

            def step(self, state, batch):
                pred = state.apply_fn(state.params, batch["x"])
                return jnp.mean((pred - batch["y"]) ** 2)

        pipeline = dml.TrainingPipeline(name="ambig")
        pipeline.append_stage(AmbiguousStage(), max_epochs=1)
        with pytest.raises(ValueError, match="Multiple unbound optimizers"):
            pipeline.run()


class _LinStage(dml.TrainValStage):
    def pre_stage(self):
        _register_model(self.pipeline, "lin")
        self.pipeline.register_optimizer("sgd", optax.sgd(0.1))
        rng = np.random.RandomState(0)
        x = rng.randn(8, 4).astype(np.float32)
        self.pipeline.register_dataset(
            "train", [{"x": x, "y": x @ rng.randn(4, 1).astype(np.float32)}], verbose=False
        )

    def step(self, state, batch):
        pred = state.apply_fn(state.params, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)


class TestValEpochErrorHandling:
    def test_missing_val_dataset_skips_validation(self, single_runtime):
        pipeline = dml.TrainingPipeline(name="noval")
        pipeline.append_stage(_LinStage(), max_epochs=1)
        pipeline.run()  # no val dataset registered: val silently skipped
        assert "val/loss" not in pipeline.tracker

    def test_user_val_dataset_valueerror_propagates(self, single_runtime):
        """A ValueError raised by a user override is a BUG — it must not be
        mistaken for "validation not configured" and swallowed forever."""

        class BuggyVal(_LinStage):
            def val_dataset(self):
                raise ValueError("user bug: bad split fraction")

        pipeline = dml.TrainingPipeline(name="buggyval")
        pipeline.append_stage(BuggyVal(), max_epochs=1)
        with pytest.raises(ValueError, match="user bug"):
            pipeline.run()

    def test_sentinel_subclasses_valueerror(self):
        # back-compat: callers catching ValueError around train_dataset()
        # keep working
        assert issubclass(DatasetNotFoundError, ValueError)
