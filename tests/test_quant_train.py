"""Quantized TRAINING (PR 16): fp32 master weights, int8 matmuls in the
step, delayed per-channel scales riding ``extras`` — and the acceptance
bound that makes the speed claim honest: the int8 loss trajectory must
track a bf16 baseline on the same seeded corpus.

Decode-time weight-only quantization lives in test_quant.py; this file
covers the ``quant_train_dot`` custom_vjp, the amax/wrap tree helpers, and
the ``TrainValStage(precision="int8")`` switch end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import dmlcloud_tpu as dml
from dmlcloud_tpu.models.quant import (
    QUANT_AMAX_KEY,
    QuantTrainTensor,
    amax_tree,
    quant_train_dot,
    wrap_train_tree,
)
from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig


# ---------------------------------------------------------------------------
# quant_train_dot: the custom_vjp
# ---------------------------------------------------------------------------


def test_quant_train_dot_forward_matches_fakequant_reference():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 6, 32).astype(np.float32))
    w = jnp.asarray(rng.randn(32, 16).astype(np.float32))
    scale = jnp.abs(w).max(axis=0, keepdims=True) / 127.0
    y = quant_train_dot(x, w, scale)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    ref = x @ (q.astype(jnp.float32) * scale)  # dequantized-weights reference
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_quant_train_dot_grads_are_straight_through():
    """dx flows through the QUANTIZED weights (what the forward used); dw is
    the straight-through fp32 estimator x^T @ g; dscale is defined-zero."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(3, 5, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    scale = jnp.abs(w).max(axis=0, keepdims=True) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    wq = q.astype(jnp.float32) * scale

    def loss(x, w, scale):
        return jnp.sum(jnp.sin(quant_train_dot(x, w, scale)))

    dx, dw, dscale = jax.grad(loss, argnums=(0, 1, 2))(x, w, scale)
    g = jnp.cos(x @ wq)  # d/dy sum(sin(y))
    np.testing.assert_allclose(np.asarray(dx), np.asarray(g @ wq.T), rtol=1e-4, atol=1e-5)
    dw_ste = jnp.einsum("bti,bto->io", x, g)  # straight-through: as if y = x @ w
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ste), rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(dscale), 0.0)


# ---------------------------------------------------------------------------
# amax_tree / wrap_train_tree
# ---------------------------------------------------------------------------


def test_amax_and_wrap_match_kernels_only():
    params = {
        "proj": {"kernel": jnp.asarray([[3.0, -1.0], [-6.0, 0.5]]), "bias": jnp.ones(2)},
        "norm": {"scale": jnp.ones(4)},
    }
    amax = amax_tree(params)
    np.testing.assert_allclose(np.asarray(amax["proj"]["kernel"]), [[6.0, 1.0]])
    assert amax["proj"]["bias"].shape == ()  # unmatched leaves: placeholder zeros
    wrapped = wrap_train_tree(params, amax)
    wk = wrapped["proj"]["kernel"]
    assert isinstance(wk, QuantTrainTensor)
    assert wk.w is params["proj"]["kernel"]  # master weights pass through untouched
    np.testing.assert_allclose(np.asarray(wk.scale), [[6.0 / 127, 1.0 / 127]])
    assert not isinstance(wrapped["proj"]["bias"], QuantTrainTensor)
    assert not isinstance(wrapped["norm"]["scale"], QuantTrainTensor)


def test_wrap_train_tree_zero_channel_scale_is_safe():
    params = {"proj": {"kernel": jnp.zeros((4, 2))}}
    wrapped = wrap_train_tree(params, amax_tree(params))
    np.testing.assert_array_equal(np.asarray(wrapped["proj"]["kernel"].scale), 1.0)
    y = quant_train_dot(jnp.ones((1, 4)), params["proj"]["kernel"],
                        wrapped["proj"]["kernel"].scale)
    assert np.isfinite(np.asarray(y)).all()


def test_precision_knob_validates():
    with pytest.raises(ValueError, match="precision"):
        dml.TrainValStage(precision="fp8")


# ---------------------------------------------------------------------------
# the acceptance test: int8 trajectory tracks bf16 through the REAL stage
# ---------------------------------------------------------------------------

_VOCAB = 64


def _lm_stage_cls(cfg, train, val, lr=1e-3):
    class LMStage(dml.TrainValStage):
        def pre_stage(self):
            model = DecoderLM(cfg)
            params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32))
            self.pipeline.register_model("lm", model, params=params, verbose=False)
            self.pipeline.register_optimizer("adamw", optax.adamw(lr))
            self.pipeline.register_dataset("train", train, verbose=False)
            self.pipeline.register_dataset("val", val, verbose=False)

        def step(self, state, batch):
            toks = batch["tokens"]
            logits = state.apply_fn({"params": state.params}, toks[:, :-1])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), toks[:, 1:]
            ).mean()

    return LMStage


def _run_arm(precision, dtype, train, val, epochs=2):
    cfg = TransformerConfig(
        vocab_size=_VOCAB, num_layers=2, num_heads=2, num_kv_heads=1, head_dim=8,
        hidden_dim=16, mlp_dim=32, max_seq_len=32, dtype=dtype,
    )
    pipe = dml.TrainingPipeline(name=f"quant-traj-{precision}")
    stage = _lm_stage_cls(cfg, train, val)(precision=precision)
    pipe.append_stage(stage, max_epochs=epochs)
    pipe.run()
    return stage


def test_int8_loss_trajectory_tracks_bf16():
    """The gate-enforced acceptance bound, in-tree: the int8 stage's
    per-epoch train losses on the pinned seeded corpus stay within 5%
    relative of the bf16 baseline's, the trajectory actually DESCENDS, and
    the delayed amax tree rides ``extras`` (full precision carries none)."""
    rng = np.random.RandomState(0)
    train = [
        {"tokens": rng.randint(0, _VOCAB, size=(8, 24)).astype(np.int32)}
        for _ in range(6)
    ]
    val = [dict(train[0])]
    bf16 = _run_arm("full", jnp.bfloat16, train, val)
    int8 = _run_arm("int8", jnp.float32, train, val)
    l_bf16 = [float(x) for x in bf16.tracker["train/loss"]]
    l_int8 = [float(x) for x in int8.tracker["train/loss"]]
    assert len(l_int8) == len(l_bf16) >= 2
    for a, b in zip(l_int8, l_bf16):
        assert abs(a - b) / abs(b) <= 0.05, (l_int8, l_bf16)
    assert l_int8[-1] < l_int8[0]  # it genuinely trains
    assert QUANT_AMAX_KEY in int8.state.extras
    assert QUANT_AMAX_KEY not in (bf16.state.extras or {})
    # master weights stay a plain fp32 tree (checkpoint/donation contract)
    assert all(
        not isinstance(x, QuantTrainTensor)
        for x in jax.tree_util.tree_leaves(
            int8.state.params,
            is_leaf=lambda x: isinstance(x, QuantTrainTensor),
        )
    )


def test_int8_amax_is_delayed_by_one_step():
    """extras carry the PREVIOUS step's post-update amax: after one step,
    the stored tree equals amax_tree of the CURRENT params (refreshed at
    step end), not of the init params."""
    rng = np.random.RandomState(3)
    train = [{"tokens": rng.randint(0, _VOCAB, size=(8, 16)).astype(np.int32)}]
    stage = _run_arm("int8", jnp.float32, train, [dict(train[0])], epochs=1)
    got = stage.state.extras[QUANT_AMAX_KEY]
    want = amax_tree(stage.state.params)
    for g, w in zip(jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)
