"""Parameter EMA: shadow-average math, sharding inheritance, stage
integration (update inside the compiled step, validation on the average),
and checkpoint/resume round-trip. No reference counterpart — torch users
bolt on ``swa_utils.AveragedModel``; here the shadow is a TrainState leaf."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import dmlcloud_tpu as dml
from dmlcloud_tpu.parallel import mesh as mesh_lib
from dmlcloud_tpu.train_state import TrainState


def test_update_ema_math():
    params = {"w": jnp.full((4,), 2.0)}
    state = TrainState.create(
        apply_fn=lambda p, x: x, params=params, tx=optax.sgd(0.0), ema=True
    )
    # ema starts as a copy of params
    np.testing.assert_allclose(state.ema["w"], 2.0)
    state = state.replace(params={"w": jnp.full((4,), 10.0)})
    state = state.update_ema(0.9)
    np.testing.assert_allclose(state.ema["w"], 0.9 * 2.0 + 0.1 * 10.0, rtol=1e-6)
    state = state.update_ema(0.9)
    np.testing.assert_allclose(state.ema["w"], 0.9 * 2.8 + 0.1 * 10.0, rtol=1e-6)


def test_update_ema_noop_without_tree():
    state = TrainState.create(
        apply_fn=lambda p, x: x, params={"w": jnp.ones(3)}, tx=optax.sgd(0.1)
    )
    assert state.ema is None
    assert state.update_ema(0.9) is state


def test_ema_true_makes_fp32_shadow_and_high_decay_still_moves():
    """decay >= 0.996 rounds to exactly 1.0 in bf16 — the shadow must be fp32
    and the blend must accumulate in fp32 or a bf16-params EMA silently
    freezes at its init value."""
    params = {"w": jnp.zeros((4,), jnp.bfloat16)}
    state = TrainState.create(apply_fn=lambda p, x: x, params=params, tx=optax.sgd(0.0), ema=True)
    assert state.ema["w"].dtype == jnp.float32
    state = state.replace(params={"w": jnp.ones((4,), jnp.bfloat16)})
    for _ in range(3):
        state = state.update_ema(0.9995)
    expected = 1.0 - 0.9995**3
    np.testing.assert_allclose(np.asarray(state.ema["w"]), expected, rtol=1e-4)


def test_ema_keeps_fp32_shadow_of_bf16_params():
    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    state = TrainState.create(
        apply_fn=lambda p, x: x,
        params=params,
        tx=optax.sgd(0.0),
        ema={"w": jnp.ones((4,), jnp.float32)},
    )
    state = state.replace(params={"w": jnp.full((4,), 3.0, jnp.bfloat16)})
    state = state.update_ema(0.5)
    assert state.ema["w"].dtype == jnp.float32
    np.testing.assert_allclose(state.ema["w"], 2.0, rtol=1e-6)


def test_update_ema_non_float_leaves_track_params():
    """An int leaf can't average (the fp32 blend truncates back to the old
    value forever) — it must follow the params directly."""
    params = {"w": jnp.ones((2,)), "steps": jnp.asarray([5], jnp.int32)}
    state = TrainState.create(apply_fn=lambda p, x: x, params=params, tx=optax.sgd(0.0), ema=True)
    assert state.ema["steps"].dtype == jnp.int32
    state = state.replace(params={"w": jnp.ones((2,)), "steps": jnp.asarray([10], jnp.int32)})
    state = state.update_ema(0.999)
    np.testing.assert_array_equal(np.asarray(state.ema["steps"]), [10])


def test_val_sees_ema_in_param_dtype():
    """The fp32 shadow must be cast back to the params' dtype for eval — a
    bf16 model's validation must not silently run fp32."""
    seen = {}

    class Probe(dml.TrainValStage):
        def ema_decay(self):
            return 0.9

        def pre_stage(self):
            params = {"w": jnp.ones((4, 1), jnp.bfloat16)}
            self.pipeline.register_model(
                "m", apply_fn=lambda v, x: x @ v["params"]["w"], params={"params": params},
                verbose=False,
            )
            self.pipeline.register_optimizer("sgd", optax.sgd(0.01))
            batch = {"x": np.ones((8, 4), np.float32)}
            self.pipeline.register_dataset("train", [batch] * 2, verbose=False)
            self.pipeline.register_dataset("val", [batch], verbose=False)

        def step(self, state, batch):
            seen.setdefault("dtypes", []).append(state.params["w"].dtype)
            pred = state.apply_fn({"params": state.params}, batch["x"])
            return jnp.mean(pred.astype(jnp.float32) ** 2)

    pipe = dml.TrainingPipeline(name="ema-dtype")
    pipe.append_stage(Probe(), max_epochs=1)
    pipe.run()
    assert all(dt == jnp.bfloat16 for dt in seen["dtypes"])


def test_ema_sharding_mirrors_params():
    mesh = mesh_lib.create_mesh({"data": 4, "model": 2})
    rules = [("a/kernel", P(None, "model")), ("b/kernel", P("model", None))]
    params = {"a": {"kernel": jnp.ones((8, 16))}, "b": {"kernel": jnp.ones((8, 16))}}
    state = TrainState.create(
        apply_fn=lambda p, x: x, params=params, tx=optax.adam(1e-3), ema=True,
        mesh=mesh, policy=rules,
    )
    sh = state.shardings(mesh, rules)
    assert sh.ema["a"]["kernel"].spec == P(None, "model")
    assert sh.ema["b"]["kernel"].spec == P("model", None)
    assert state.ema["a"]["kernel"].sharding.spec == P(None, "model")


class _EmaStage(dml.TrainValStage):
    """Linear-regression toy: val loss on EMA params differs measurably from
    val loss on raw params while the average trails the moving params."""

    def __init__(self, decay):
        super().__init__()
        self._decay = decay

    def ema_decay(self):
        return self._decay

    def pre_stage(self):
        import flax.linen as nn

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(1, use_bias=False)(x)

        model = Lin()
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))
        self.pipeline.register_model("lin", model, params=params, verbose=False)
        self.pipeline.register_optimizer("sgd", optax.sgd(0.3))
        rng = np.random.RandomState(0)
        xs = rng.randn(8, 16, 4).astype(np.float32)
        batches = [{"x": x, "y": (x @ np.array([[1.0], [2.0], [3.0], [4.0]])).astype(np.float32)} for x in xs]
        self.pipeline.register_dataset("train", batches, verbose=False)
        self.pipeline.register_dataset("val", batches[:2], verbose=False)

    def step(self, state, batch):
        pred = state.apply_fn({"params": state.params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)


def _run(decay, epochs=2):
    pipe = dml.TrainingPipeline(name="ema-test")
    stage = _EmaStage(decay)
    pipe.append_stage(stage, max_epochs=epochs)
    pipe.run()
    return stage


def test_stage_ema_trails_params_and_val_uses_it():
    stage = _run(decay=0.95)
    params = np.asarray(stage.state.params["Dense_0"]["kernel"]).ravel()
    ema = np.asarray(stage.state.ema["Dense_0"]["kernel"]).ravel()
    target = np.array([1.0, 2.0, 3.0, 4.0])
    # params converge toward the target; the EMA trails strictly behind
    assert np.linalg.norm(params - target) < np.linalg.norm(ema - target)
    assert not np.allclose(ema, params)
    # the validation metric was computed on the EMA params: recompute both
    # losses by hand and check which one the tracker recorded
    xs = np.asarray(stage.pipeline.datasets["val"][0]["x"])
    ys = np.asarray(stage.pipeline.datasets["val"][0]["y"])

    def loss_of(w):
        return float(np.mean((xs @ w.reshape(4, 1) - ys) ** 2))

    recorded = stage.tracker["val/loss"][-1]
    # tracker averaged over the two val batches of the last epoch
    xs2 = np.asarray(stage.pipeline.datasets["val"][1]["x"])
    ys2 = np.asarray(stage.pipeline.datasets["val"][1]["y"])
    ema_loss = 0.5 * (loss_of(ema) + float(np.mean((xs2 @ ema.reshape(4, 1) - ys2) ** 2)))
    raw_loss = 0.5 * (
        loss_of(params) + float(np.mean((xs2 @ params.reshape(4, 1) - ys2) ** 2))
    )
    assert abs(recorded - ema_loss) < 1e-5
    assert abs(recorded - raw_loss) > 1e-7  # and NOT the raw-params loss


def test_stage_without_ema_has_no_shadow():
    stage = _run(decay=0.0)
    assert stage.state.ema is None


def test_ema_enabled_after_checkpoint_resumes_from_restored_params(tmp_path):
    """Toggling ema_decay() on across a resume must not break restore; the
    fresh shadow starts from the restored params, not the random init."""
    pipe = dml.TrainingPipeline(name="ema-toggle")
    pipe.enable_checkpointing(str(tmp_path), resume=False)
    pipe.append_stage(_EmaStage(0.0), max_epochs=2)
    pipe.run()
    trained = np.asarray(pipe.stages[0].state.params["Dense_0"]["kernel"])

    pipe2 = dml.TrainingPipeline(name="ema-toggle")
    pipe2.enable_checkpointing(str(pipe.checkpoint_dir.path), resume=True)
    stage2 = _EmaStage(0.9)
    pipe2.append_stage(stage2, max_epochs=2)
    pipe2.run()
    assert stage2.state.ema is not None
    # no new epochs ran (already complete), so the shadow equals the restored params
    np.testing.assert_allclose(
        np.asarray(stage2.state.ema["Dense_0"]["kernel"]), trained, rtol=1e-6
    )


def test_ema_disabled_after_checkpoint_drops_shadow(tmp_path):
    pipe = dml.TrainingPipeline(name="ema-toggle-off")
    pipe.enable_checkpointing(str(tmp_path), resume=False)
    pipe.append_stage(_EmaStage(0.9), max_epochs=2)
    pipe.run()

    pipe2 = dml.TrainingPipeline(name="ema-toggle-off")
    pipe2.enable_checkpointing(str(pipe.checkpoint_dir.path), resume=True)
    stage2 = _EmaStage(0.0)
    pipe2.append_stage(stage2, max_epochs=2)
    pipe2.run()
    assert stage2.state.ema is None


def test_ema_checkpoint_resume(tmp_path):
    pipe = dml.TrainingPipeline(name="ema-ckpt")
    pipe.enable_checkpointing(str(tmp_path), resume=False)
    stage = _EmaStage(0.9)
    pipe.append_stage(stage, max_epochs=2)
    pipe.run()
    saved_ema = np.asarray(stage.state.ema["Dense_0"]["kernel"])

    pipe2 = dml.TrainingPipeline(name="ema-ckpt")
    pipe2.enable_checkpointing(str(pipe.checkpoint_dir.path), resume=True)
    stage2 = _EmaStage(0.9)
    pipe2.append_stage(stage2, max_epochs=2)  # already-complete: restore, no new epochs
    pipe2.run()
    np.testing.assert_allclose(
        np.asarray(stage2.state.ema["Dense_0"]["kernel"]), saved_ema, rtol=1e-6
    )
