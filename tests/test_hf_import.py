"""HF Llama checkpoint import: converted params must reproduce the live
HuggingFace model's logits (which pins the RoPE convention permutation, all
transposes, GQA head mapping, norm placement, and the lm head)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402

from dmlcloud_tpu.models.hf import llama_params_from_hf, transformer_config_from_hf  # noqa: E402
from dmlcloud_tpu.models.transformer import DecoderLM  # noqa: E402


def _tiny_hf(tie=False, kv_heads=2):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=61,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=kv_heads,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    return hf_cfg, model


@pytest.mark.parametrize("tie,kv_heads", [(False, 2), (False, 4), (True, 2)])
def test_logits_match_hf(tie, kv_heads):
    hf_cfg, hf_model = _tiny_hf(tie=tie, kv_heads=kv_heads)
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.tie_embeddings == tie
    params = llama_params_from_hf(hf_model.state_dict(), cfg)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, hf_cfg.vocab_size, size=(2, 11))

    with torch.no_grad():
        want = hf_model(torch.from_numpy(tokens)).logits.numpy()
    got = DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def test_generate_from_hf_weights():
    """Converted weights drive the KV-cache decode loop: greedy generation
    equals HF's own greedy generation."""
    hf_cfg, hf_model = _tiny_hf()
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    params = llama_params_from_hf(hf_model.state_dict(), cfg)

    from dmlcloud_tpu.models.generate import generate

    rng = np.random.RandomState(1)
    prompt = rng.randint(0, hf_cfg.vocab_size, size=(1, 7))
    with torch.no_grad():
        want = hf_model.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0, eos_token_id=None,
        ).numpy()[:, 7:]
    got = generate(DecoderLM(cfg), params, jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_missing_weight_raises():
    hf_cfg, hf_model = _tiny_hf()
    cfg = transformer_config_from_hf(hf_cfg)
    sd = dict(hf_model.state_dict())
    sd.pop("model.layers.0.self_attn.q_proj.weight")
    with pytest.raises(KeyError, match="q_proj"):
        llama_params_from_hf(sd, cfg)


def test_unconverted_weight_raises():
    hf_cfg, hf_model = _tiny_hf()
    cfg = transformer_config_from_hf(hf_cfg)
    sd = dict(hf_model.state_dict())
    sd["model.layers.0.unexpected.weight"] = torch.zeros(2)
    with pytest.raises(ValueError, match="unconverted"):
        llama_params_from_hf(sd, cfg)
