"""HF Llama checkpoint import: converted params must reproduce the live
HuggingFace model's logits (which pins the RoPE convention permutation, all
transposes, GQA head mapping, norm placement, and the lm head)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402

from dmlcloud_tpu.models.hf import llama_params_from_hf, transformer_config_from_hf  # noqa: E402
from dmlcloud_tpu.models.transformer import DecoderLM  # noqa: E402


def _tiny_hf(tie=False, kv_heads=2):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=61,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=kv_heads,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    return hf_cfg, model


@pytest.mark.slow
@pytest.mark.parametrize("tie,kv_heads", [(False, 2), (False, 4), (True, 2)])
def test_logits_match_hf(tie, kv_heads):
    hf_cfg, hf_model = _tiny_hf(tie=tie, kv_heads=kv_heads)
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.tie_embeddings == tie
    params = llama_params_from_hf(hf_model.state_dict(), cfg)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, hf_cfg.vocab_size, size=(2, 11))

    with torch.no_grad():
        want = hf_model(torch.from_numpy(tokens)).logits.numpy()
    got = DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_generate_from_hf_weights():
    """Converted weights drive the KV-cache decode loop: greedy generation
    equals HF's own greedy generation."""
    hf_cfg, hf_model = _tiny_hf()
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    params = llama_params_from_hf(hf_model.state_dict(), cfg)

    from dmlcloud_tpu.models.generate import generate

    rng = np.random.RandomState(1)
    prompt = rng.randint(0, hf_cfg.vocab_size, size=(1, 7))
    with torch.no_grad():
        want = hf_model.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0, eos_token_id=None,
        ).numpy()[:, 7:]
    got = generate(DecoderLM(cfg), params, jnp.asarray(prompt), max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_missing_weight_raises():
    hf_cfg, hf_model = _tiny_hf()
    cfg = transformer_config_from_hf(hf_cfg)
    sd = dict(hf_model.state_dict())
    sd.pop("model.layers.0.self_attn.q_proj.weight")
    with pytest.raises(KeyError, match="q_proj"):
        llama_params_from_hf(sd, cfg)


def test_unconverted_weight_raises():
    hf_cfg, hf_model = _tiny_hf()
    cfg = transformer_config_from_hf(hf_cfg)
    sd = dict(hf_model.state_dict())
    sd["model.layers.0.unexpected.weight"] = torch.zeros(2)
    with pytest.raises(ValueError, match="unconverted"):
        llama_params_from_hf(sd, cfg)


def test_mistral_config_and_logits():
    """Mistral = same architecture + sliding_window; converted weights must
    match the HF Mistral forward (whose eager attention applies the window)."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=61,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        sliding_window=6,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    hf_model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.sliding_window == 6
    params = llama_params_from_hf(hf_model.state_dict(), cfg)

    tokens = np.random.RandomState(3).randint(0, 61, size=(2, 13))
    with torch.no_grad():
        want = hf_model(torch.from_numpy(tokens)).logits.numpy()
    got = DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def test_export_round_trips_through_hf():
    """params -> HF state dict -> load into a live HF model -> logits match;
    and importing the exported dict reproduces the original params."""
    from dmlcloud_tpu.models.hf import hf_state_dict_from_params

    hf_cfg, hf_model = _tiny_hf()
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    params = llama_params_from_hf(hf_model.state_dict(), cfg)

    sd = hf_state_dict_from_params(params, cfg)
    fresh = transformers.LlamaForCausalLM(hf_cfg).eval()
    fresh.load_state_dict({k: torch.from_numpy(v) for k, v in sd.items()})

    tokens = np.random.RandomState(4).randint(0, 61, size=(1, 10))
    with torch.no_grad():
        want = hf_model(torch.from_numpy(tokens)).logits.numpy()
        got = fresh(torch.from_numpy(tokens)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)

    # exact param round trip (same treedef => leaves align positionally)
    back = llama_params_from_hf(sd, cfg)
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(back)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_decoupled_head_dim():
    """Mistral-Nemo-style configs set head_dim independently of
    hidden_size // num_heads."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=61, hidden_size=40, intermediate_size=64, num_hidden_layers=1,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        max_position_embeddings=64, sliding_window=None, attn_implementation="eager",
    )
    torch.manual_seed(3)
    hf_model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.head_dim == 16 and cfg.hidden_dim == 40
    params = llama_params_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.RandomState(5).randint(0, 61, size=(1, 9))
    with torch.no_grad():
        want = hf_model(torch.from_numpy(tokens)).logits.numpy()
    got = DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def test_tied_export_loads_strict():
    from dmlcloud_tpu.models.hf import hf_state_dict_from_params

    hf_cfg, hf_model = _tiny_hf(tie=True)
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    params = llama_params_from_hf(hf_model.state_dict(), cfg)
    sd = hf_state_dict_from_params(params, cfg)
    fresh = transformers.LlamaForCausalLM(hf_cfg).eval()
    fresh.load_state_dict({k: torch.from_numpy(v) for k, v in sd.items()})  # strict
    tokens = np.random.RandomState(6).randint(0, 61, size=(1, 8))
    with torch.no_grad():
        want = hf_model(torch.from_numpy(tokens)).logits.numpy()
        got = fresh(torch.from_numpy(tokens)).logits.numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_beam_search_matches_hf():
    """Same weights, same K: our jitted beam search must produce HF
    generate(num_beams=K)'s tokens."""
    from dmlcloud_tpu.models.generate import beam_search

    hf_cfg, hf_model = _tiny_hf()
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    params = llama_params_from_hf(hf_model.state_dict(), cfg)
    prompt = np.random.RandomState(7).randint(0, 61, size=(2, 6))

    toks, _ = beam_search(DecoderLM(cfg), params, jnp.asarray(prompt), max_new_tokens=8, num_beams=4)
    with torch.no_grad():
        want = hf_model.generate(
            torch.from_numpy(prompt), max_new_tokens=8, num_beams=4, do_sample=False,
            pad_token_id=0, eos_token_id=None, length_penalty=1.0, early_stopping=False,
        ).numpy()[:, 6:]
    np.testing.assert_array_equal(np.asarray(toks), want)


@pytest.mark.parametrize(
    "rope_scaling",
    [
        {"rope_type": "linear", "factor": 2.0},
        {
            "rope_type": "llama3",
            "factor": 4.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 32,
        },
    ],
)
def test_rope_scaled_logits_match_hf(rope_scaling):
    """Llama-3 / linear rope scaling must reproduce HF's scaled rotary
    geometry, not silently fall back to plain RoPE."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=61, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_scaling=dict(rope_scaling), attn_implementation="eager",
    )
    torch.manual_seed(4)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = transformer_config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.rope_scaling is not None
    params = llama_params_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.RandomState(8).randint(0, 61, size=(2, 40))  # long enough to scale
    with torch.no_grad():
        want = hf_model(torch.from_numpy(tokens)).logits.numpy()
    got = DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=3e-4)


def test_unsupported_rope_scaling_raises():
    class FakeCfg:
        vocab_size, num_hidden_layers, num_attention_heads = 61, 1, 4
        num_key_value_heads, hidden_size, intermediate_size = 2, 32, 64
        max_position_embeddings, rope_theta = 64, 10000.0
        tie_word_embeddings, sliding_window = False, None
        head_dim = 8
        rope_scaling = {"rope_type": "yarn", "factor": 2.0}

    with pytest.raises(ValueError, match="yarn"):
        transformer_config_from_hf(FakeCfg())


def test_rope_scaling_without_type_key_raises():
    from dmlcloud_tpu.models.hf import _rope_scaling_from_hf

    with pytest.raises(ValueError, match="rope_type"):
        _rope_scaling_from_hf({"factor": 8.0})
    assert _rope_scaling_from_hf(None) is None
    assert _rope_scaling_from_hf({"rope_type": "default"}) is None
