"""TensorBoard sink: per-epoch tracker scalars land in event files that
TensorBoard's own reader parses back (third observability channel next to
the console table and wandb)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import dmlcloud_tpu as dml
from dmlcloud_tpu.utils.tensorboard import tensorboard_available

def _reader_available() -> bool:
    try:
        from tensorboard.backend.event_processing import event_accumulator  # noqa: F401

        return True
    except ImportError:
        return False


pytestmark = pytest.mark.skipif(
    not (tensorboard_available() and _reader_available()),
    reason="tensorboardX (writer) or tensorboard (test reader) not installed",
)


class _TinyStage(dml.TrainValStage):
    def pre_stage(self):
        import flax.linen as nn

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(1, use_bias=False)(x)

        model = Lin()
        self.pipeline.register_model(
            "lin", model, params=model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4))),
            verbose=False,
        )
        self.pipeline.register_optimizer("sgd", optax.sgd(0.1))
        rng = np.random.RandomState(0)
        xs = rng.randn(4, 16, 4).astype(np.float32)
        self.pipeline.register_dataset(
            "train", [{"x": x, "y": x.sum(1, keepdims=True)} for x in xs], verbose=False
        )

    def step(self, state, batch):
        pred = state.apply_fn({"params": state.params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    def val_epoch(self):
        pass


def _read_scalars(logdir):
    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    acc = EventAccumulator(str(logdir))
    acc.Reload()
    return {tag: [(e.step, e.value) for e in acc.Scalars(tag)] for tag in acc.Tags()["scalars"]}


def test_scalars_written_per_epoch(tmp_path):
    pipe = dml.TrainingPipeline(name="tb-test")
    pipe.enable_tensorboard(str(tmp_path / "tb"))
    pipe.append_stage(_TinyStage(), max_epochs=3)
    pipe.run()
    scalars = _read_scalars(tmp_path / "tb")
    assert "train/loss" in scalars, sorted(scalars)
    steps = [s for s, _ in scalars["train/loss"]]
    assert steps == [1, 2, 3]
    # values are the tracker's reduced per-epoch losses
    hist = pipe.stages[0].tracker["train/loss"]
    np.testing.assert_allclose([v for _, v in scalars["train/loss"]], hist, rtol=1e-6)


def test_default_logdir_needs_checkpointing(tmp_path):
    pipe = dml.TrainingPipeline(name="tb-test2")
    pipe.enable_tensorboard()  # default dir = <checkpoint_dir>/tb
    pipe.append_stage(_TinyStage(), max_epochs=1)
    with pytest.raises(ValueError, match="checkpointing"):
        pipe.run()


def test_default_logdir_under_checkpoint_dir(tmp_path):
    pipe = dml.TrainingPipeline(name="tb-test3")
    pipe.enable_checkpointing(str(tmp_path), resume=False)
    pipe.enable_tensorboard()
    pipe.append_stage(_TinyStage(), max_epochs=2)
    pipe.run()
    tb_dir = pipe.checkpoint_dir.path / "tb"
    scalars = _read_scalars(tb_dir)
    assert "train/loss" in scalars and len(scalars["train/loss"]) == 2
