"""Kernel-numerics property tests (PR 6's raw-speed pass, tier-1).

The optimisation sweep rewrote the hot kernels' lowerings — these tests pin
the numerics so the speed can't drift away from correctness:

- flash attention fwd AND fwd+bwd must match the unfused einsum reference
  within per-dtype tolerance across dtypes (bf16/fp32), causal/window
  variants, ragged (non-block-multiple) lengths, and BOTH lowerings — the
  blockwise-XLA off-TPU default and the interpreted Pallas kernels;
- speculative decode must stay token-identical to plain greedy decode when
  draft == target (the provably-accept-everything contract whose breakage
  produced the r05 receipts' 0.0 accept rate).

Shapes are kept small so the whole module runs inside tier-1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.ops.flash_attention import _reference_attention, flash_attention

# (impl kwarg, interpret kwarg): the blockwise-XLA lowering and the
# bit-exact interpreted Pallas kernels — both must hold the same contract
LOWERINGS = [("xla", None), ("pallas", True)]

TOL = {
    jnp.float32: dict(atol=5e-5, rtol=5e-5),
    # bf16 inputs: both sides accumulate in fp32 but round operands/outputs
    # to 8 mantissa bits; gradients compound one extra rounding
    jnp.bfloat16: dict(atol=6e-2, rtol=6e-2),
}


def _qkv(b=2, t=64, h=4, kh=None, d=16, seed=0, dtype=jnp.float32):
    kh = kh or h
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, t, h, d), dtype) * 0.5
    k = jnp.asarray(rng.randn(b, t, kh, d), dtype) * 0.5
    v = jnp.asarray(rng.randn(b, t, kh, d), dtype)
    return q, k, v


def _grads(attn, q, k, v, cot):
    loss = lambda q, k, v: jnp.vdot(attn(q, k, v).astype(jnp.float32), cot.astype(jnp.float32))
    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


class TestFlashFwdBwdProperty:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16], ids=["fp32", "bf16"])
    @pytest.mark.parametrize("impl,interp", LOWERINGS, ids=["xla", "pallas"])
    @pytest.mark.parametrize("causal,window", [(True, None), (False, None), (True, 24)],
                             ids=["causal", "full", "window24"])
    def test_fwd_and_grads_match_reference(self, dtype, impl, interp, causal, window):
        q, k, v = _qkv(dtype=dtype)
        sm = 1.0 / np.sqrt(q.shape[-1])
        tol = TOL[dtype]

        flash = lambda q, k, v: flash_attention(
            q, k, v, causal=causal, window=window, block_q=32, block_k=32,
            impl=impl, interpret=interp,
        )
        ref = lambda q, k, v: _reference_attention(q, k, v, causal, sm, window=window)

        np.testing.assert_allclose(
            np.asarray(flash(q, k, v), np.float32), np.asarray(ref(q, k, v), np.float32),
            err_msg="forward", **tol,
        )
        cot = jnp.asarray(np.random.RandomState(7).randn(*q.shape), jnp.float32)
        got = _grads(flash, q, k, v, cot)
        want = _grads(ref, q, k, v, cot)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(w, np.float32),
                err_msg=f"d{name}", **tol,
            )

    @pytest.mark.parametrize("t", [40, 56, 96], ids=lambda t: f"t{t}")
    @pytest.mark.parametrize("impl,interp", LOWERINGS, ids=["xla", "pallas"])
    def test_ragged_lengths(self, t, impl, interp):
        """Non-block-multiple sequence lengths: the auto-shrunk block grid
        (40 -> blocks of 8, 56 -> 8, 96 -> 32) must stay exact fwd+bwd."""
        q, k, v = _qkv(t=t)
        sm = 1.0 / np.sqrt(q.shape[-1])

        flash = lambda q, k, v: flash_attention(q, k, v, causal=True, impl=impl, interpret=interp)
        ref = lambda q, k, v: _reference_attention(q, k, v, True, sm)

        np.testing.assert_allclose(
            np.asarray(flash(q, k, v)), np.asarray(ref(q, k, v)), atol=5e-5, rtol=5e-5
        )
        cot = jnp.asarray(np.random.RandomState(3).randn(*q.shape), jnp.float32)
        got = _grads(flash, q, k, v, cot)
        want = _grads(ref, q, k, v, cot)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
            )

    def test_gqa_grads_both_lowerings_agree(self):
        """The two lowerings of the SAME algorithm must agree with each
        other (not just each within tolerance of the reference) — GQA
        grouping included."""
        q, k, v = _qkv(t=64, h=8, kh=2)
        cot = jnp.asarray(np.random.RandomState(5).randn(*q.shape), jnp.float32)
        xla = _grads(lambda q, k, v: flash_attention(q, k, v, causal=True, impl="xla"), q, k, v, cot)
        pal = _grads(
            lambda q, k, v: flash_attention(q, k, v, causal=True, impl="pallas", interpret=True,
                                            block_q=32, block_k=32),
            q, k, v, cot,
        )
        for g, w, name in zip(xla, pal, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=1e-5, rtol=1e-5, err_msg=f"d{name}"
            )


class TestSpeculativeExactness:
    def test_shared_model_token_identical(self):
        """draft == target: every proposal must be accepted and the output
        must equal plain greedy decode token for token."""
        from dmlcloud_tpu.models.generate import generate
        from dmlcloud_tpu.models.speculative import speculative_generate
        from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig

        cfg = TransformerConfig(
            vocab_size=32, num_layers=2, num_heads=2, num_kv_heads=1, head_dim=8,
            hidden_dim=16, mlp_dim=32, max_seq_len=48, dtype=jnp.float32,
        )
        model = DecoderLM(cfg)
        prompt = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 6)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]

        want = np.asarray(generate(model, params, prompt, max_new_tokens=12))
        got, (rounds, _, accepted) = speculative_generate(
            model, params, model, params, prompt, max_new_tokens=12, k=3, return_stats=True
        )
        np.testing.assert_array_equal(np.asarray(got), want)
        rounds, accepted = np.asarray(rounds, np.float64), np.asarray(accepted, np.float64)
        np.testing.assert_allclose(accepted / (rounds * 3), 1.0)
