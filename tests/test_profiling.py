"""Profiling helpers: real jax.profiler traces land on disk, profile_steps
returns the computed result, StepTimer percentiles behave."""

import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.utils.profiling import StepTimer, profile_steps, trace


@pytest.mark.slow
def test_trace_writes_profile(tmp_path):
    logdir = tmp_path / "prof"
    with trace(str(logdir)):
        x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
        float(x.sum())
    files = list(logdir.rglob("*"))
    assert any(f.is_file() for f in files), "no trace artifacts written"


def test_profile_steps_returns_result(tmp_path):
    def step():
        return jnp.arange(4.0) * 2

    out = profile_steps(step, 3, str(tmp_path / "prof"))
    np.testing.assert_array_equal(np.asarray(out), [0.0, 2.0, 4.0, 6.0])


def test_step_timer_percentiles():
    t = StepTimer()
    t.tick()
    for _ in range(10):
        t.tick()
    assert t.count == 10
    summary = t.summary()
    assert summary["p50_ms"] >= 0.0
    assert summary["p95_ms"] >= summary["p50_ms"]
    assert summary["max_ms"] >= summary["p95_ms"]
    assert StepTimer().summary() == {}
