"""Profiling helpers: real jax.profiler traces land on disk, profile_steps
returns the computed result, StepTimer percentiles behave."""

import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.utils.profiling import StepTimer, profile_steps, trace


@pytest.mark.slow
def test_trace_writes_profile(tmp_path):
    logdir = tmp_path / "prof"
    with trace(str(logdir)):
        x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
        float(x.sum())
    files = list(logdir.rglob("*"))
    assert any(f.is_file() for f in files), "no trace artifacts written"


def test_profile_steps_returns_result(tmp_path):
    def step():
        return jnp.arange(4.0) * 2

    out = profile_steps(step, 3, str(tmp_path / "prof"))
    np.testing.assert_array_equal(np.asarray(out), [0.0, 2.0, 4.0, 6.0])


def test_step_timer_percentiles():
    t = StepTimer()
    t.tick()
    for _ in range(10):
        t.tick()
    assert t.count == 10
    summary = t.summary()
    assert summary["p50_ms"] >= 0.0
    assert summary["p95_ms"] >= summary["p50_ms"]
    assert summary["max_ms"] >= summary["p95_ms"]
    assert StepTimer().summary() == {}


def test_roofline_requires_trace_dir(tmp_path):
    import pytest

    from dmlcloud_tpu.utils.profiling import roofline

    with pytest.raises(FileNotFoundError, match="xplane"):
        roofline(str(tmp_path))


def test_format_roofline_renders_without_peaks():
    from dmlcloud_tpu.utils.profiling import format_roofline

    peaks = {"device": "X", "peak_tflops": 0.0, "peak_hbm_gbps": 0.0}
    rows = [
        {"category": "fusion", "time_frac": 0.9, "ms_per_step": 1.0, "tflops": 2.0, "gbps": 10.0, "n_per_step": 3},
        {"category": "tiny", "time_frac": 0.0001, "ms_per_step": 0.0, "tflops": 0.0, "gbps": 0.0, "n_per_step": 1},
    ]
    out = format_roofline(peaks, rows)
    assert "fusion" in out and "tiny" not in out  # sub-0.1% rows hidden
    assert "% of peak" not in out  # no bogus percentage from a zero peak


def test_peak_flops_for_kind():
    from dmlcloud_tpu.utils.profiling import chip_peak_flops, peak_flops_for_kind

    assert peak_flops_for_kind("TPU v5 lite") == 197e12
    assert peak_flops_for_kind("TPU v6e") == 918e12
    assert peak_flops_for_kind("cpu") is None
    assert chip_peak_flops() > 0  # falls back on unknown kinds


class TestStallTimerNesting:
    """StallTimer.measure() nesting-safety: nested spans (block()/fetch()
    called inside an outer measure()) must not double-count — only the
    outermost span accumulates."""

    @staticmethod
    def _with_fake_clock(monkeypatch):
        """Each perf_counter_ns read advances a fake clock by exactly 1 ms,
        making the accounting arithmetic deterministic."""
        from dmlcloud_tpu.utils import profiling

        clock = {"ns": 0}

        def fake_ns():
            clock["ns"] += 1_000_000
            return clock["ns"]

        monkeypatch.setattr(profiling.time, "perf_counter_ns", fake_ns)
        return clock

    def test_nested_measure_counts_outer_span_once(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        self._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure():          # clock read #1 (enter, 1ms)
            with t.measure():      # nested: NO clock read
                pass
            with t.measure():      # nested: NO clock read
                pass
        # clock read #2 (exit, 2ms): exactly one 1ms outer span accumulated.
        # The pre-fix accounting read the clock in every measure() and
        # would have reported 3 overlapping spans here.
        assert t.ms == 1.0

    def test_nested_fetch_and_block_accumulate_once(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        self._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure():
            t.fetch(np.ones(3))            # rides the outer span
            t.block({"x": np.ones(2)})     # rides the outer span
        assert t.ms == 1.0

    def test_sequential_measures_still_sum(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        self._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure():
            pass
        with t.measure():
            pass
        assert t.ms == 2.0
        t.reset()
        assert t.ms == 0.0

    def test_real_clock_sanity(self):
        import time as _time

        from dmlcloud_tpu.utils.profiling import StallTimer

        t = StallTimer()
        with t.measure():
            with t.measure():
                _time.sleep(0.01)
        # one ~10ms span, not ~20ms of double-counted overlap
        assert 5.0 <= t.ms < 1000.0
