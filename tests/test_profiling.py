"""Profiling helpers: real jax.profiler traces land on disk, profile_steps
returns the computed result, StepTimer percentiles behave."""

import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.utils.profiling import StepTimer, profile_steps, trace


@pytest.mark.slow
def test_trace_writes_profile(tmp_path):
    logdir = tmp_path / "prof"
    with trace(str(logdir)):
        x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
        float(x.sum())
    files = list(logdir.rglob("*"))
    assert any(f.is_file() for f in files), "no trace artifacts written"


def test_profile_steps_returns_result(tmp_path):
    def step():
        return jnp.arange(4.0) * 2

    out = profile_steps(step, 3, str(tmp_path / "prof"))
    np.testing.assert_array_equal(np.asarray(out), [0.0, 2.0, 4.0, 6.0])


def test_step_timer_percentiles():
    t = StepTimer()
    t.tick()
    for _ in range(10):
        t.tick()
    assert t.count == 10
    summary = t.summary()
    assert summary["p50_ms"] >= 0.0
    assert summary["p95_ms"] >= summary["p50_ms"]
    assert summary["p99_ms"] >= summary["p95_ms"]
    assert summary["max_ms"] >= summary["p99_ms"]
    assert summary["total_ms"] == pytest.approx(sum(t._t))
    assert StepTimer().summary() == {}


def test_step_timer_reset_forgets_last_tick():
    t = StepTimer()
    t.tick()
    t.tick()
    assert t.count == 1
    t.reset()
    assert t.count == 0 and t.summary() == {}
    # the first tick after reset starts a NEW sequence: no phantom interval
    # spanning the reset gap
    t.tick()
    assert t.count == 0
    t.tick()
    assert t.count == 1


def test_roofline_requires_trace_dir(tmp_path):
    import pytest

    from dmlcloud_tpu.utils.profiling import roofline

    with pytest.raises(FileNotFoundError, match="xplane"):
        roofline(str(tmp_path))


def test_format_roofline_renders_without_peaks():
    from dmlcloud_tpu.utils.profiling import format_roofline

    peaks = {"device": "X", "peak_tflops": 0.0, "peak_hbm_gbps": 0.0}
    rows = [
        {"category": "fusion", "time_frac": 0.9, "ms_per_step": 1.0, "tflops": 2.0, "gbps": 10.0, "n_per_step": 3},
        {"category": "tiny", "time_frac": 0.0001, "ms_per_step": 0.0, "tflops": 0.0, "gbps": 0.0, "n_per_step": 1},
    ]
    out = format_roofline(peaks, rows)
    assert "fusion" in out and "tiny" not in out  # sub-0.1% rows hidden
    assert "% of peak" not in out  # no bogus percentage from a zero peak


def _load_analyze_trace():
    import importlib.util
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "scripts" / "analyze_trace.py"
    if not path.is_file():
        pytest.skip("scripts/ not present next to the package")
    spec = importlib.util.spec_from_file_location("_analyze_trace_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_analyze_trace_json_schema(monkeypatch, capsys):
    import json

    mod = _load_analyze_trace()
    peaks = {"device": "X", "peak_tflops": 1.0, "peak_hbm_gbps": 2.0}
    rows = [
        {"category": "fusion", "time_frac": 1.0, "ms_per_step": 1.0,
         "tflops": 1.0, "gbps": 1.0, "n_per_step": 1},
    ]
    monkeypatch.setattr(mod, "roofline", lambda d, steps=30: (peaks, rows))
    assert mod.main(["/tmp/whatever", "--json", "--steps", "7"]) == 0
    out = json.loads(capsys.readouterr().out)
    # v2 is ADDITIVE over v1: the roofline keys are locked unchanged
    # (serve-journal inputs add a "serve" object instead — see
    # tests/test_observability.py)
    assert out["version"] == 2
    assert out["steps"] == 7
    assert out["peaks"] == peaks and out["rows"] == rows


def test_analyze_trace_empty_rows_is_a_clear_message(monkeypatch, capsys):
    mod = _load_analyze_trace()
    peaks = {"device": "X", "peak_tflops": 1.0, "peak_hbm_gbps": 2.0}
    monkeypatch.setattr(mod, "roofline", lambda d, steps=30: (peaks, []))
    assert mod.main(["/tmp/whatever"]) == 1
    err = capsys.readouterr().err
    assert "no XLA op rows" in err and "block_until_ready" in err
    assert mod.main(["/tmp/whatever", "--json"]) == 1  # same guard on the json path


def test_peak_flops_for_kind():
    from dmlcloud_tpu.utils.profiling import chip_peak_flops, peak_flops_for_kind

    assert peak_flops_for_kind("TPU v5 lite") == 197e12
    assert peak_flops_for_kind("TPU v6e") == 918e12
    assert peak_flops_for_kind("cpu") is None
    assert chip_peak_flops() > 0  # falls back on unknown kinds


class TestStallTimerNesting:
    """StallTimer.measure() nesting-safety: nested spans (block()/fetch()
    called inside an outer measure()) must not double-count — only the
    outermost span accumulates."""

    @staticmethod
    def _with_fake_clock(monkeypatch):
        """Each perf_counter_ns read advances a fake clock by exactly 1 ms,
        making the accounting arithmetic deterministic."""
        from dmlcloud_tpu.utils import profiling

        clock = {"ns": 0}

        def fake_ns():
            clock["ns"] += 1_000_000
            return clock["ns"]

        monkeypatch.setattr(profiling.time, "perf_counter_ns", fake_ns)
        return clock

    def test_nested_measure_counts_outer_span_once(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        self._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure():          # clock read #1 (enter, 1ms)
            with t.measure():      # nested: NO clock read
                pass
            with t.measure():      # nested: NO clock read
                pass
        # clock read #2 (exit, 2ms): exactly one 1ms outer span accumulated.
        # The pre-fix accounting read the clock in every measure() and
        # would have reported 3 overlapping spans here.
        assert t.ms == 1.0

    def test_nested_fetch_and_block_accumulate_once(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        self._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure():
            t.fetch(np.ones(3))            # rides the outer span
            t.block({"x": np.ones(2)})     # rides the outer span
        assert t.ms == 1.0

    def test_sequential_measures_still_sum(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        self._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure():
            pass
        with t.measure():
            pass
        assert t.ms == 2.0
        t.reset()
        assert t.ms == 0.0

    def test_real_clock_sanity(self):
        import time as _time

        from dmlcloud_tpu.utils.profiling import StallTimer

        t = StallTimer()
        with t.measure():
            with t.measure():
                _time.sleep(0.01)
        # one ~10ms span, not ~20ms of double-counted overlap
        assert 5.0 <= t.ms < 1000.0


class TestStallTimerLabels:
    """measure(label=...) attributes spans to named buckets — how the
    goodput ledger splits checkpoint waits from metric readbacks — and, with
    the telemetry journal armed, emits them as typed spans."""

    def test_labels_accumulate_separately(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        TestStallTimerNesting._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure(label="checkpoint"):
            pass
        with t.measure(label="checkpoint"):
            pass
        with t.measure(label="metric_readback"):
            pass
        with t.measure():  # unlabeled: total only
            pass
        assert t.label_ms("checkpoint") == 2.0
        assert t.label_ms("metric_readback") == 1.0
        assert t.label_ms("nope") == 0.0
        assert t.ms == 4.0

    def test_nested_label_attributes_outermost_only(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        TestStallTimerNesting._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure(label="checkpoint"):
            with t.measure(label="metric_readback"):  # nested: no span of its own
                pass
        assert t.label_ms("checkpoint") == 1.0
        assert t.label_ms("metric_readback") == 0.0

    def test_reset_clears_labels(self, monkeypatch):
        from dmlcloud_tpu.utils.profiling import StallTimer

        TestStallTimerNesting._with_fake_clock(monkeypatch)
        t = StallTimer()
        with t.measure(label="checkpoint"):
            pass
        t.reset()
        assert t.ms == 0.0 and t.label_ms("checkpoint") == 0.0

    def test_labeled_span_reaches_journal(self, tmp_path):
        from dmlcloud_tpu.telemetry import journal as journal_mod
        from dmlcloud_tpu.telemetry.journal import SpanJournal
        from dmlcloud_tpu.utils.profiling import StallTimer

        j = journal_mod.activate(SpanJournal(tmp_path))
        try:
            t = StallTimer()
            with t.measure(label="checkpoint"):
                pass
            with t.measure(label="custom_wait"):  # not a v1 kind
                pass
            with t.measure():  # unlabeled: no journal span
                pass
        finally:
            journal_mod.deactivate()
        recs = j.tail(10)
        assert [r["kind"] for r in recs] == ["checkpoint", "host_stall"]
        assert recs[1]["label"] == "custom_wait"  # label preserved as attr
        j.close()
