"""Config container: attribute/dict access, YAML round-trip, and the
OmegaConf-style ``${...}`` interpolation semantics the reference relies on
(/root/reference/dmlcloud/pipeline.py:154,269-270, checkpoint.py:105-117)."""

import pytest

from dmlcloud_tpu.utils.config import Config, InterpolationError, as_config


class TestBasics:
    def test_attribute_and_item_access(self):
        cfg = Config({"model": {"width": 8}, "lr": 0.1})
        assert cfg.model.width == 8
        assert cfg["model"]["width"] == 8
        assert cfg.get("missing", 3) == 3

    def test_yaml_roundtrip(self, tmp_path):
        cfg = Config({"a": 1, "nested": {"b": [1, 2]}})
        cfg.save(tmp_path / "c.yaml")
        loaded = Config.load(tmp_path / "c.yaml")
        assert loaded.to_dict() == cfg.to_dict()

    def test_as_config(self):
        assert as_config(None).to_dict() == {}
        assert as_config({"x": 1}).x == 1
        with pytest.raises(TypeError):
            as_config(42)


class TestInterpolation:
    def test_typed_reference(self):
        cfg = Config({"model": {"width": 128}, "head_dim": "${model.width}"})
        assert cfg.head_dim == 128  # int, not "128"

    def test_string_substitution(self):
        cfg = Config({"name": "run", "out": "results/${name}/logs"})
        assert cfg.out == "results/run/logs"

    def test_chained_references(self):
        cfg = Config({"a": 4, "b": "${a}", "c": "${b}"})
        assert cfg.c == 4

    def test_reference_from_nested_node(self):
        cfg = Config({"lr": 0.1, "optim": {"lr": "${lr}"}})
        assert cfg.optim.lr == 0.1  # resolved against the ROOT

    def test_dangling_reference_raises(self):
        cfg = Config({"x": "${nope.deep}"})
        with pytest.raises(InterpolationError, match="does not resolve"):
            _ = cfg.x

    def test_cycle_raises(self):
        cfg = Config({"a": "${b}", "b": "${a}"})
        with pytest.raises(InterpolationError, match="cycle"):
            _ = cfg.a

    def test_env_resolver(self, monkeypatch):
        monkeypatch.setenv("DML_TEST_VAR", "hello")
        cfg = Config({"x": "${env:DML_TEST_VAR}", "y": "${env:DML_MISSING_VAR,fallback}"})
        assert cfg.x == "hello"
        assert cfg.y == "fallback"
        with pytest.raises(InterpolationError, match="not set"):
            _ = Config({"z": "${env:DML_MISSING_VAR}"}).z

    def test_to_dict_resolved_vs_raw(self):
        cfg = Config({"a": 2, "b": "${a}"})
        assert cfg.to_dict() == {"a": 2, "b": "${a}"}  # raw by default
        assert cfg.to_dict(resolve=True) == {"a": 2, "b": 2}
        assert "${a}" in cfg.to_yaml()
        assert "${a}" not in cfg.to_yaml(resolve=True)

    def test_save_keeps_interpolations(self, tmp_path):
        """Like OmegaConf.save: the stored config keeps ${...} so a resumed
        run re-resolves against its (possibly overridden) context."""
        cfg = Config({"a": 1, "b": "${a}"})
        cfg.save(tmp_path / "c.yaml")
        loaded = Config.load(tmp_path / "c.yaml")
        loaded["a"] = 7
        assert loaded.b == 7

    def test_resolve_materialises(self):
        frozen = Config({"a": 1, "b": "${a}"}).resolve()
        frozen["a"] = 9
        assert frozen.b == 1  # no longer linked

    def test_node_alias_resolves_and_dumps(self):
        """A whole-string interpolation may target a mapping node; resolved
        dumps must produce plain YAML, and access must traverse the alias."""
        cfg = Config({"model": {"lr": 0.1}, "alias": "${model}"})
        assert cfg.alias.lr == 0.1
        d = cfg.to_dict(resolve=True)
        assert d["alias"] == {"lr": 0.1} and type(d["alias"]) is dict
        assert "lr: 0.1" in cfg.to_yaml(resolve=True)  # no RepresenterError

    def test_interpolation_inside_lists(self):
        cfg = Config({"w": 5, "layers": [{"dim": "${w}"}, "${w}"]})
        assert cfg.layers == [{"dim": 5}, 5]
        assert cfg.to_dict(resolve=True)["layers"] == [{"dim": 5}, 5]

    def test_assigning_subconfig_does_not_corrupt_source(self):
        base = Config({"a": 1, "m": {"x": "${a}"}})
        other = Config({})
        other["m"] = base["m"]  # copies; must NOT re-parent base's node
        assert base.m.x == 1  # source tree still resolves
        with pytest.raises(InterpolationError):
            _ = other.m.x  # the copy resolves against ITS root, which lacks 'a'

    def test_copying_config_keeps_interpolations_raw(self):
        cfg = Config({"port": "${env:DML_UNSET_PORT,8080}", "opt": "${maybe.later}"})
        copy = Config(cfg)  # must not materialise or raise
        assert copy.to_dict() == cfg.to_dict()
        copy["maybe"] = {"later": 3}
        assert copy.opt == 3

    def test_container_values_stay_live_without_interpolation(self):
        """Reads of plain containers return the STORED object — in-place
        mutation must persist (only interpolation-bearing values rebuild)."""
        cfg = Config({"tags": ["a"]})
        cfg.tags.append("b")
        assert cfg.tags == ["a", "b"]

    def test_copies_do_not_share_list_storage(self):
        """Forking a config must not alias mutable containers: mutating the
        fork (or the original) stays local to it."""
        base = Config({"tags": ["a"], "nested": {"xs": [1]}})
        fork = Config(base)
        fork.tags.append("debug")
        fork.nested.xs.append(2)
        assert base.tags == ["a"]
        assert base.nested.xs == [1]

    def test_reference_through_alias_segment(self):
        """A dotted path whose intermediate segment is itself an alias."""
        cfg = Config({"model": {"lr": 0.1}, "alias": "${model}", "x": "${alias.lr}"})
        assert cfg.x == 0.1

    def test_string_substitution_of_node_raises(self):
        cfg = Config({"model": {"lr": 0.1}, "p": "out/${model}"})
        with pytest.raises(InterpolationError, match="not a scalar"):
            _ = cfg.p

    def test_xr_process_group_positional_slot(self):
        """The reference signature has process_group at position 11; passing
        one must raise, not silently shift load/load_kwargs."""
        from dmlcloud_tpu.data import ShardedXrDataset

        with pytest.raises(ValueError, match="process_group"):
            ShardedXrDataset(None, "t", 2, 0, True, True, False, 0, 0, 1, object(), True)
