"""LoRA adapters: zero-init identity, merge math, matcher behavior, and an
end-to-end finetune through TrainValStage where ONLY the adapters train
(base rides state.extras untouched) yet the merged model's loss drops."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import dmlcloud_tpu as dml
from dmlcloud_tpu.models.lora import default_match, lora_init, lora_merge, lora_size


def _base_params():
    rng = np.random.RandomState(0)
    return {
        "dense": {"kernel": jnp.asarray(rng.randn(6, 4), jnp.float32), "bias": jnp.zeros(4)},
        "attn": {"q": {"kernel": jnp.asarray(rng.randn(6, 2, 3), jnp.float32)}},
        "norm": {"scale": jnp.ones(6)},
    }


def test_zero_init_merge_is_identity():
    base = _base_params()
    adapters = lora_init(jax.random.PRNGKey(0), base, rank=2)
    merged = lora_merge(base, adapters)
    for a, b in zip(jax.tree_util.tree_leaves(merged), jax.tree_util.tree_leaves(base)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_merge_math_and_3d_kernel_reshape():
    base = _base_params()
    adapters = lora_init(jax.random.PRNGKey(0), base, rank=2)
    # poke b so the delta is nonzero
    adapters["attn"]["q"]["kernel"] = adapters["attn"]["q"]["kernel"].replace(
        b=jnp.ones((2, 3), jnp.float32)
    )
    alpha = 16.0
    merged = lora_merge(base, adapters, alpha=alpha)
    a = np.asarray(adapters["attn"]["q"]["kernel"].a)  # [12, 2]: leading [6,2] collapsed
    delta = (a @ np.ones((2, 3), np.float32)) * (alpha / 2)
    expected = np.asarray(base["attn"]["q"]["kernel"]) + delta.reshape(6, 2, 3)
    np.testing.assert_allclose(np.asarray(merged["attn"]["q"]["kernel"]), expected, rtol=1e-6)
    # non-adapted leaves pass through
    np.testing.assert_array_equal(np.asarray(merged["norm"]["scale"]), np.ones(6))


def test_default_match_and_regex_match():
    base = _base_params()
    default = lora_init(jax.random.PRNGKey(0), base, rank=2)
    assert default["dense"]["kernel"] is not None
    assert default["attn"]["q"]["kernel"] is not None
    assert default["dense"]["bias"] is None and default["norm"]["scale"] is None
    only_attn = lora_init(jax.random.PRNGKey(0), base, rank=2, match=r"attn/.*kernel")
    assert only_attn["dense"]["kernel"] is None
    assert only_attn["attn"]["q"]["kernel"] is not None
    # dense [6,4]: a [6,2] + b [2,4]; attn [6,2,3] collapses leading axes
    # to in=12: a [12,2] + b [2,3]
    assert lora_size(default) == (6 * 2 + 2 * 4) + (12 * 2 + 2 * 3)
    assert lora_size(only_attn) == 12 * 2 + 2 * 3


def test_grads_flow_only_through_adapters():
    base = _base_params()
    adapters = lora_init(jax.random.PRNGKey(1), base, rank=2)

    def loss(ad):
        merged = lora_merge(base, ad)
        return jnp.sum(merged["dense"]["kernel"] ** 2) + jnp.sum(
            merged["attn"]["q"]["kernel"] ** 2
        )

    grads = jax.grad(loss)(adapters)
    # b is zero but its grad is not (a^T @ dL/dW != 0); a's grad IS zero at
    # b=0 (dL/da = dL/dW @ b^T) — the classic LoRA first-step structure
    assert float(jnp.abs(grads["dense"]["kernel"].b).sum()) > 0
    np.testing.assert_allclose(np.asarray(grads["dense"]["kernel"].a), 0.0)


def test_lora_partition_rules_replicate_adapters_only():
    from jax.sharding import PartitionSpec as P

    from dmlcloud_tpu.models.lora import lora_partition_rules
    from dmlcloud_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.create_mesh({"data": 4, "model": 2})
    base = {"attn": {"q_proj": {"kernel": jnp.ones((8, 16))}}}
    adapters = lora_init(jax.random.PRNGKey(0), base, rank=2)
    rules = lora_partition_rules([("attn/.*kernel", P(None, "model"))])
    base_sh = mesh_lib.sharding_for(base, mesh, rules)
    ad_sh = mesh_lib.sharding_for(adapters, mesh, rules)
    # the base kernel still shards; its adapter factors replicate even
    # though the base rule's regex also matches ".../kernel/a"
    assert base_sh["attn"]["q_proj"]["kernel"].spec == P(None, "model")
    assert ad_sh["attn"]["q_proj"]["kernel"].a.spec == P()
    assert ad_sh["attn"]["q_proj"]["kernel"].b.spec == P()


def _mlp_and_base():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(8)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    model = MLP()
    return model, model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))["params"]


class _LoraLMStage(dml.TrainValStage):
    """Tiny regression head finetuned via adapters only."""

    def pre_stage(self):
        model, base = _mlp_and_base()
        adapters = lora_init(jax.random.PRNGKey(1), base, rank=2)
        self.pipeline.register_model(
            "mlp",
            apply_fn=model.apply,
            params={"params": adapters, "lora_base": base},
            verbose=False,
        )
        self.pipeline.register_optimizer("adamw", optax.adamw(3e-2))
        rng = np.random.RandomState(0)
        xs = rng.randn(6, 32, 4).astype(np.float32)
        w = np.array([[0.5], [-1.0], [2.0], [0.3]], np.float32)
        self.pipeline.register_dataset(
            "train", [{"x": x, "y": x @ w} for x in xs], verbose=False
        )

    def step(self, state, batch):
        merged = lora_merge(state.extras["lora_base"], state.params)
        pred = state.apply_fn({"params": merged}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    def val_epoch(self):
        pass


def test_stage_finetunes_adapters_only():
    pipe = dml.TrainingPipeline(name="lora-test")
    stage = _LoraLMStage()
    pipe.append_stage(stage, max_epochs=4)
    pipe.run()
    hist = stage.tracker["train/loss"]
    assert hist[-1] < hist[0] * 0.7, hist
    # the frozen base never moved ...
    base_after = stage.state.extras["lora_base"]
    _, fresh = _mlp_and_base()
    for a, b in zip(jax.tree_util.tree_leaves(base_after), jax.tree_util.tree_leaves(fresh)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ... while the adapters did
    assert float(jnp.abs(stage.state.params["Dense_0"]["kernel"].b).sum()) > 0
    # optimizer state is adapter-sized, not model-sized
    n_opt = sum(int(x.size) for x in jax.tree_util.tree_leaves(stage.state.opt_state))
    assert n_opt < 3 * lora_size(stage.state.params) + 8

