"""Native C++ interleave kernel: correctness vs the numpy fallback, and the
end-to-end interleave_batches path using it (skipped when the .so isn't built;
CI builds it via native/build.sh)."""

import numpy as np
import pytest

from dmlcloud_tpu.native import interleave as native


requires_native = pytest.mark.skipif(not native.available(), reason="libdmltpu.so not built")


@requires_native
def test_native_matches_python():
    n, bs = 4, 16
    rng = np.random.RandomState(0)
    batches = [rng.randn(bs, 5).astype(np.float32) for _ in range(n)]
    s = bs // n

    mem = np.empty((n, bs, 5), np.float32)
    native.interleave_into(mem, batches, s)

    ref = np.empty_like(mem)
    for i in range(n):
        for j in range(n):
            ref[i, j * s : (j + 1) * s] = batches[j][i * s : (i + 1) * s]
    np.testing.assert_array_equal(mem, ref)


@requires_native
def test_native_1d_batches():
    n = 2
    batches = [np.arange(4, dtype=np.int64), np.arange(4, 8, dtype=np.int64)]
    mem = np.empty((n, 4), np.int64)
    native.interleave_into(mem, batches, 2)
    np.testing.assert_array_equal(mem[0], [0, 1, 4, 5])
    np.testing.assert_array_equal(mem[1], [2, 3, 6, 7])


@requires_native
def test_interleave_batches_uses_native_path():
    from dmlcloud_tpu.data import interleave_batches

    batches = [np.random.RandomState(i).randn(8, 4).astype(np.float32) for i in range(4)]
    out = [b.copy() for b in interleave_batches(iter(batches), 4)]
    all_in = np.sort(np.concatenate(batches).ravel())
    all_out = np.sort(np.concatenate(out).ravel())
    np.testing.assert_array_equal(all_in, all_out)
