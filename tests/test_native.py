"""Native C++ interleave kernel: correctness vs the numpy fallback, and the
end-to-end interleave_batches path using it (skipped when the .so isn't built;
CI builds it via native/build.sh)."""

import numpy as np
import pytest

from dmlcloud_tpu.native import interleave as native
from dmlcloud_tpu.native import pack as native_pack


requires_native = pytest.mark.skipif(not native.available(), reason="libdmltpu.so not built")


@requires_native
def test_native_matches_python():
    n, bs = 4, 16
    rng = np.random.RandomState(0)
    batches = [rng.randn(bs, 5).astype(np.float32) for _ in range(n)]
    s = bs // n

    mem = np.empty((n, bs, 5), np.float32)
    native.interleave_into(mem, batches, s)

    ref = np.empty_like(mem)
    for i in range(n):
        for j in range(n):
            ref[i, j * s : (j + 1) * s] = batches[j][i * s : (i + 1) * s]
    np.testing.assert_array_equal(mem, ref)


@requires_native
def test_native_1d_batches():
    n = 2
    batches = [np.arange(4, dtype=np.int64), np.arange(4, 8, dtype=np.int64)]
    mem = np.empty((n, 4), np.int64)
    native.interleave_into(mem, batches, 2)
    np.testing.assert_array_equal(mem[0], [0, 1, 4, 5])
    np.testing.assert_array_equal(mem[1], [2, 3, 6, 7])


@requires_native
def test_interleave_batches_uses_native_path():
    from dmlcloud_tpu.data import interleave_batches

    batches = [np.random.RandomState(i).randn(8, 4).astype(np.float32) for i in range(4)]
    out = [b.copy() for b in interleave_batches(iter(batches), 4)]
    all_in = np.sort(np.concatenate(batches).ravel())
    all_out = np.sort(np.concatenate(out).ravel())
    np.testing.assert_array_equal(all_in, all_out)


class TestNativePacker:
    """C++ pack.cpp must be bit-identical to data.pack_sequences across
    split modes, long/empty/exact-fit examples, and the flat-buffer path."""

    pytestmark = pytest.mark.skipif(not native_pack.available(), reason="libdmltpu.so not built")

    def _corpus(self, seed=0, n=400, max_len=40):
        rng = np.random.RandomState(seed)
        pieces = [rng.randint(1, 99, size=rng.randint(1, max_len)) for _ in range(n)]
        pieces += [
            rng.randint(1, 99, size=130),  # longer than seq_len: split/truncate
            np.zeros(0, np.int64),  # empty: skipped
            rng.randint(1, 99, size=64),  # exact row fit
        ]
        return pieces

    @pytest.mark.parametrize("split_long", [True, False])
    def test_bit_identical_to_python(self, split_long):
        from dmlcloud_tpu.data.datasets import pack_sequences
        from dmlcloud_tpu.native.pack import pack_sequences_fast

        pieces = self._corpus()
        want = list(pack_sequences([p.copy() for p in pieces], 64, split_long=split_long))
        got = pack_sequences_fast([p.copy() for p in pieces], 64, split_long=split_long)
        assert len(want) == len(got)
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a["tokens"], b["tokens"])
            np.testing.assert_array_equal(a["segment_ids"], b["segment_ids"])

    def test_pack_flat_matches(self):
        from dmlcloud_tpu.data.datasets import pack_sequences
        from dmlcloud_tpu.native.pack import pack_flat

        pieces = [np.asarray(p, np.int32) for p in self._corpus(seed=3)]
        lengths = np.asarray([p.size for p in pieces], np.int64)
        flat = np.concatenate(pieces)
        tokens, segs = pack_flat(flat, lengths, 64)
        want = list(pack_sequences(pieces, 64))
        np.testing.assert_array_equal(np.stack([r["tokens"] for r in want]), tokens)
        np.testing.assert_array_equal(np.stack([r["segment_ids"] for r in want]), segs)

    def test_pack_flat_validates_lengths(self):
        from dmlcloud_tpu.native.pack import pack_flat

        with pytest.raises(ValueError, match="lengths sum"):
            pack_flat(np.zeros(5, np.int32), np.asarray([3], np.int64), 8)

    def test_empty_corpus(self):
        from dmlcloud_tpu.native.pack import pack_sequences_fast

        assert pack_sequences_fast([], 16) == []

    def test_pack_flat_rejects_negative_lengths(self):
        from dmlcloud_tpu.native.pack import pack_flat

        with pytest.raises(ValueError, match="non-negative"):
            pack_flat(np.zeros(5, np.int32), np.asarray([-3, 8], np.int64), 16)
