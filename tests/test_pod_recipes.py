"""The pod-scale recipes (BASELINE configs 4-5) must run end to end on the
8-device CPU mesh in toy mode — same code path as the v5p-64 invocations
documented in their module docstrings (mesh + partition rules + remat +
chunked loss + Orbax step checkpointing), only the sizes differ."""

import os
import sys

import pytest

_EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")
sys.path.insert(0, _EXAMPLES)

pytestmark = pytest.mark.slow


def _run(module_name, argv, monkeypatch):
    import importlib

    mod = importlib.import_module(module_name)
    monkeypatch.setattr(sys, "argv", [f"{module_name}.py"] + argv)
    return mod.main()


def test_pod_clip_vit_toy(tmp_path, monkeypatch):
    stage = _run(
        "pod_clip_vit",
        ["--toy", "--mesh", "data=2,fsdp=4", "--checkpoint-dir", str(tmp_path)],
        monkeypatch,
    )
    loss = [float(v) for v in stage.tracker["train/loss"]]
    acc = [float(v) for v in stage.tracker["train/accuracy"]]
    assert len(loss) == 2  # toy caps at 2 epochs
    assert loss[-1] < loss[0], loss  # the contrastive objective has signal
    assert acc[-1] >= acc[0], acc
    run_dir = next(p for p in tmp_path.iterdir() if p.is_dir())
    assert (run_dir / "config.yaml").exists()
    assert (run_dir / "log.txt").stat().st_size > 0


def test_pod_llama_fsdp_toy(tmp_path, monkeypatch):
    stage = _run(
        "pod_llama_fsdp",
        [
            "--toy", "--mesh", "data=2,fsdp=4", "--remat", "--chunked-loss", "128",
            "--grad-accum", "2", "--epochs", "2",
            "--checkpoint-dir", str(tmp_path), "--save-every-steps", "3",
        ],
        monkeypatch,
    )
    loss = [float(v) for v in stage.tracker["train/loss"]]
    assert len(loss) == 2 and loss[-1] < loss[0], loss
    # the sharded params really follow llama_partition_rules on this mesh:
    # every rule names fsdp first, so at least the big kernels must be split
    spec = stage.state.params["lm_head"]["kernel"].sharding.spec
    assert "fsdp" in str(spec), spec
    run_dir = next(p for p in tmp_path.iterdir() if p.is_dir())
    assert (run_dir / "config.yaml").exists()
    # step-granular Orbax saves landed (cadence 3 over 4-step epochs)
    state_dir = run_dir / "state"
    assert state_dir.exists() and any(state_dir.iterdir())
