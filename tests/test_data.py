"""Data-sharding parity suite, modeled on the reference's test/test_data.py:
pure-function shard math (even/uneven/drop/shuffle), multi-rank behavior via
explicit rank parameterization, chunked/overlapping xr-style sharding against
a duck-typed dataset, interleave content checks, prefetch/batch wrappers."""

import numpy as np
import pytest

from dmlcloud_tpu.data import (
    BatchDataset,
    PrefetchDataset,
    ShardedSequenceDataset,
    ShardedXrDataset,
    chunk_and_shard_indices,
    interleave_batches,
    interleave_dict_batches,
    shard_indices,
    shard_sequence,
    sharded_xr_dataset,
)


class FakeXr:
    """Duck-typed stand-in for xarray: .isel + dim lookup + .load."""

    def __init__(self, data: np.ndarray, dim: str = "time"):
        self.data = data
        self.dim = dim
        self.loaded = False

    def __getitem__(self, dim):
        assert dim == self.dim
        return self.data

    def isel(self, indexers):
        sl = indexers[self.dim]
        return FakeXr(self.data[sl], self.dim)

    def load(self):
        self.loaded = True


class TestShardIndices:
    def test_even(self):
        assert shard_indices(10, 0, 2) == [0, 2, 4, 6, 8]
        assert shard_indices(10, 1, 2) == [1, 3, 5, 7, 9]

    def test_uneven_drops_remainder(self):
        assert shard_indices(11, 0, 2) == [0, 2, 4, 6, 8]
        assert shard_indices(11, 1, 2) == [1, 3, 5, 7, 9]

    def test_uneven_keep_remainder(self):
        assert shard_indices(11, 0, 2, even_shards=False) == [0, 2, 4, 6, 8, 10]
        assert shard_indices(11, 1, 2, even_shards=False) == [1, 3, 5, 7, 9]

    def test_shuffle_deterministic(self):
        a = shard_indices(10, 0, 2, shuffle=True, seed=7)
        b = shard_indices(10, 0, 2, shuffle=True, seed=7)
        c = shard_indices(10, 0, 2, shuffle=True, seed=8)
        assert a == b
        assert a != c

    def test_shuffle_partitions(self):
        parts = [shard_indices(10, r, 2, shuffle=True, seed=3) for r in range(2)]
        assert sorted(parts[0] + parts[1]) == list(range(10))

    def test_python_ints(self):
        assert all(type(i) is int for i in shard_indices(6, 0, 3))


class TestChunkAndShard:
    def test_basic(self):
        # 10 elements, chunks of 2 -> 5 chunks; rank0 gets chunks 0,2 (even_shards drops chunk 4)
        assert chunk_and_shard_indices(10, 2, 0, 2) == [(0, 2), (4, 6)]
        assert chunk_and_shard_indices(10, 2, 1, 2) == [(2, 4), (6, 8)]

    def test_overlap(self):
        chunks = chunk_and_shard_indices(10, 2, 0, 2, chunk_overlap=1)
        assert chunks == [(0, 3), (4, 7)]

    def test_unequal_chunks(self):
        chunks = chunk_and_shard_indices(10, 3, 0, 1, equal_chunks=False, even_shards=False)
        assert chunks == [(0, 3), (3, 6), (6, 9), (9, 12)]


class TestShardSequence:
    def test_basic(self):
        assert shard_sequence("abcdef", 1, 2) == ["b", "d", "f"]


class TestShardedXr:
    @pytest.mark.parametrize("world_size", [1, 2, 3])
    def test_rank_partition(self, world_size):
        data = np.arange(12)
        ds = FakeXr(data)
        seen = []
        for r in range(world_size):
            for chunk in sharded_xr_dataset(ds, "time", 2, rank=r, world_size=world_size):
                seen.extend(chunk.data.tolist())
        n_chunks = 12 // 2
        expected_chunks = n_chunks - n_chunks % world_size
        assert len(seen) == expected_chunks * 2
        assert sorted(seen) == sorted(range(expected_chunks * 2))

    def test_overlap_windows(self):
        ds = FakeXr(np.arange(10))
        chunks = list(sharded_xr_dataset(ds, "time", 2, chunk_overlap=1, rank=0, world_size=2))
        np.testing.assert_array_equal(chunks[0].data, [0, 1, 2])
        np.testing.assert_array_equal(chunks[1].data, [4, 5, 6])

    def test_load_flag(self):
        ds = FakeXr(np.arange(4))
        chunks = list(sharded_xr_dataset(ds, "time", 2, rank=0, world_size=1, load=True))
        assert all(c.loaded for c in chunks)

    def test_dataset_class_set_epoch_reshuffles(self, single_runtime):
        ds = FakeXr(np.arange(20))
        sharded = ShardedXrDataset(ds, "time", 2, shuffle=True, seed=0, rank=0, world_size=2)
        first = [c.data.tolist() for c in sharded]
        sharded.set_epoch(1)
        second = [c.data.tolist() for c in sharded]
        assert first != second


class TestShardedSequenceDataset:
    def test_partition(self, single_runtime):
        ds0 = ShardedSequenceDataset(list(range(8)), rank=0, world_size=2)
        ds1 = ShardedSequenceDataset(list(range(8)), rank=1, world_size=2)
        assert list(ds0) == [0, 2, 4, 6]
        assert list(ds1) == [1, 3, 5, 7]
        assert len(ds0) == 4

    def test_set_epoch_reshuffles(self, single_runtime):
        ds = ShardedSequenceDataset(list(range(16)), shuffle=True, rank=0, world_size=2)
        a = list(ds)
        ds.set_epoch(1)
        b = list(ds)
        assert a != b

    def test_dataloader_worker_subsharding(self, single_runtime):
        """Under a torch DataLoader with 2 workers, the (rank, worker) grid
        partitions the data exactly (reference test_data.py:171-363)."""
        torch = pytest.importorskip("torch")
        from torch.utils.data import DataLoader

        seen = []
        for rank in range(2):
            ds = ShardedSequenceDataset(list(range(16)), rank=rank, world_size=2)
            dl = DataLoader(ds, batch_size=None, num_workers=2)
            seen.extend(int(x) for x in dl)
        assert sorted(seen) == list(range(16))


class TestWrappers:
    def test_prefetch_preserves_order(self):
        ds = PrefetchDataset(list(range(20)), num_elements=4)
        assert list(ds) == list(range(20))

    def test_batch_dataset(self):
        ds = BatchDataset(list(range(7)), batch_size=3)
        assert list(ds) == [[0, 1, 2], [3, 4, 5], [6]]
        assert len(ds) == 3

    def test_batch_dataset_drop_remainder(self):
        ds = BatchDataset(list(range(7)), batch_size=3, drop_remainder=True)
        assert list(ds) == [[0, 1, 2], [3, 4, 5]]
        assert len(ds) == 2

    def test_set_epoch_forwarding(self, single_runtime):
        inner = ShardedSequenceDataset(list(range(4)), rank=0, world_size=1)
        ds = BatchDataset(inner, batch_size=2)
        ds.set_epoch(3)
        assert inner.epoch == 3


class TestDataPipeline:
    """The combinator core behind the parity shims."""

    def test_chain_shard_batch_collate(self, single_runtime):
        from dmlcloud_tpu.data import DataPipeline

        p = (
            DataPipeline.from_sequence(list(range(16)), rank=0, world_size=2)
            .map(float)
            .batch(4, collate=np.asarray)
        )
        out = list(p)
        assert len(out) == 2 and len(p) == 2
        np.testing.assert_array_equal(out[0], [0.0, 2.0, 4.0, 6.0])

    def test_epoch_threads_through_chain(self, single_runtime):
        """set_epoch on the FINAL pipeline re-seeds the shuffling source —
        no per-wrapper forwarding protocol needed."""
        from dmlcloud_tpu.data import DataPipeline

        p = DataPipeline.from_sequence(list(range(32)), shuffle=True, rank=0, world_size=2).batch(4)
        a = [list(b) for b in p]
        p.set_epoch(1)
        b = [list(b) for b in p]
        assert a != b
        p.set_epoch(0)
        assert [list(x) for x in p] == a  # deterministic per epoch

    def test_interleave_combinator_with_dicts(self, single_runtime):
        from dmlcloud_tpu.data import DataPipeline

        batches = [
            {"x": np.arange(4) + 4 * i, "y": np.arange(2) + 2 * i} for i in range(2)
        ]
        p = DataPipeline.from_source(batches).interleave(2)
        out = [{k: v.copy() for k, v in b.items()} for b in p]
        np.testing.assert_array_equal(out[0]["x"], [0, 1, 4, 5])
        np.testing.assert_array_equal(out[0]["y"], [0, 2])

    def test_interleave_then_prefetch_no_corruption(self, single_runtime):
        """Lookahead stages hold several batches at once; interleave output
        must not be rewritten under them by the next window."""
        from dmlcloud_tpu.data import DataPipeline

        batches = [np.full(4, i) for i in range(4)]
        p = DataPipeline.from_source(batches).interleave(2).prefetch(4)
        out = list(p)  # fully buffered before consumption
        np.testing.assert_array_equal(out[0], [0, 0, 1, 1])
        np.testing.assert_array_equal(out[1], [0, 0, 1, 1])
        np.testing.assert_array_equal(out[2], [2, 2, 3, 3])

    def test_inner_epoch_respected_when_wrapper_not_driven(self, single_runtime):
        """set_epoch on the INNER dataset (reference sampler idiom) must hold
        when the outer wrapper's epoch was never set."""
        inner = ShardedSequenceDataset(list(range(16)), shuffle=True, rank=0, world_size=2)
        inner.set_epoch(5)
        baseline = list(inner)
        wrapped = BatchDataset(inner, 2)  # wrapped.set_epoch never called
        inner.set_epoch(5)
        assert [x for b in wrapped for x in b] == baseline

    def test_prefetch_abandoned_consumer_stops_producer(self, single_runtime):
        """Early exit from a prefetched loop must release the producer thread
        (it would otherwise block on the full queue forever, every epoch)."""
        import threading
        import time

        from dmlcloud_tpu.data import DataPipeline

        before = threading.active_count()
        it = iter(DataPipeline.from_source(range(100000)).prefetch(2))
        assert next(it) == 0
        it.close()
        deadline = time.time() + 5
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before

    def test_prefetch_propagates_source_error(self, single_runtime):
        from dmlcloud_tpu.data import DataPipeline

        def gen():
            yield 1
            raise RuntimeError("boom")

        p = DataPipeline.from_source(gen()).prefetch(2)
        with pytest.raises(RuntimeError, match="boom"):
            list(p)

    def test_to_device_yields_sharded_batches(self, single_runtime):
        import jax

        from dmlcloud_tpu.data import DataPipeline
        from dmlcloud_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.create_mesh({"data": 8})
        batches = [{"x": np.arange(16, dtype=np.float32).reshape(16, 1) + i} for i in range(3)]
        out = list(DataPipeline.from_source(batches).to_device(mesh))
        assert len(out) == 3
        assert isinstance(out[0]["x"], jax.Array)
        assert out[0]["x"].sharding.spec == mesh_lib.batch_pspec(mesh)

    def test_device_iterator_host_prefetch_preserves_order(self, single_runtime):
        """Background-thread host batch prep must not reorder, drop, or
        corrupt batches relative to the plain path."""
        import jax

        from dmlcloud_tpu.data.device import device_iterator
        from dmlcloud_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.create_mesh({"data": 8})
        batches = [{"x": np.full((16, 1), i, np.float32)} for i in range(6)]
        plain = list(device_iterator(iter(batches), mesh, prefetch=2))
        threaded = list(device_iterator(iter(batches), mesh, prefetch=2, host_prefetch=3))
        assert len(plain) == len(threaded) == 6
        for a, b in zip(plain, threaded):
            np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
            assert isinstance(b["x"], jax.Array)

    def test_device_iterator_prefetch_zero_is_strictly_synchronous(self, single_runtime):
        """Depth 0 must transfer NOTHING ahead of consumption: after pulling
        one batch, exactly one batch has been read from the source (the old
        behavior eagerly transferred one batch ahead)."""
        from dmlcloud_tpu.data.device import device_iterator
        from dmlcloud_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.create_mesh({"data": 8})
        pulled = []

        def source():
            for i in range(4):
                pulled.append(i)
                yield {"x": np.full((16, 1), i, np.float32)}

        it = device_iterator(source(), mesh, prefetch=0)
        assert pulled == []  # nothing moves before the first next()
        first = next(it)
        assert pulled == [0]
        np.testing.assert_array_equal(np.asarray(first["x"]), np.full((16, 1), 0.0))
        next(it)
        assert pulled == [0, 1]
        assert len(list(it)) == 2  # the remainder still arrives, in order
        # contrast: depth 2 keeps transfers in flight ahead of consumption
        pulled.clear()
        it2 = device_iterator(source(), mesh, prefetch=2)
        next(it2)
        assert pulled == [0, 1]  # one batch ahead already in flight

    def test_peek_spec_reiterable_untouched(self, single_runtime):
        from dmlcloud_tpu.data.device import peek_spec

        batches = [{"x": np.zeros((4, 2), np.float32)} for _ in range(3)]
        spec, out = peek_spec(batches)
        assert out is batches  # re-iterable sources come back untouched
        assert spec["x"].shape == (4, 2) and spec["x"].dtype == np.float32
        assert len(list(out)) == 3

    def test_peek_spec_one_shot_iterator_replays_first_batch(self, single_runtime):
        from dmlcloud_tpu.data.device import peek_spec

        src = ({"x": np.full((2,), i, np.float32)} for i in range(3))
        spec, out = peek_spec(src)
        assert spec["x"].shape == (2,)
        vals = [int(b["x"][0]) for b in out]
        assert vals == [0, 1, 2]  # the peeked batch is not lost

    def test_peek_spec_empty_dataset_raises(self, single_runtime):
        import pytest

        from dmlcloud_tpu.data.device import peek_spec

        with pytest.raises(ValueError, match="empty"):
            peek_spec([])

    def test_shims_pickle_roundtrip(self, single_runtime):
        """DataLoader workers receive datasets by pickle; the shims must
        survive the round trip with epoch intact."""
        import pickle

        ds = ShardedSequenceDataset(list(range(8)), shuffle=True, rank=0, world_size=2)
        ds.set_epoch(3)
        clone = pickle.loads(pickle.dumps(ds))
        assert clone.epoch == 3
        assert list(clone) == list(ds)

        wrapped = BatchDataset(ShardedSequenceDataset(list(range(8)), rank=0, world_size=1), 2)
        clone2 = pickle.loads(pickle.dumps(wrapped))
        assert [list(b) for b in clone2] == [list(b) for b in wrapped]


class TestInterleave:
    def test_content(self):
        # Two batches of 4 -> two mixed batches, each half from each source.
        b0 = np.arange(4)
        b1 = np.arange(4, 8)
        out = [b.copy() for b in interleave_batches([b0, b1], 2)]
        np.testing.assert_array_equal(out[0], [0, 1, 4, 5])
        np.testing.assert_array_equal(out[1], [2, 3, 6, 7])

    def test_roundtrip_multidim(self):
        batches = [np.random.RandomState(i).randn(6, 3) for i in range(3)]
        out = [b.copy() for b in interleave_batches(batches, 3)]
        all_in = np.sort(np.concatenate(batches).ravel())
        all_out = np.sort(np.concatenate(out).ravel())
        np.testing.assert_array_equal(all_in, all_out)

    def test_single_passthrough(self):
        batches = [np.arange(4)]
        assert [b.tolist() for b in interleave_batches(batches, 1)] == [[0, 1, 2, 3]]

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            list(interleave_batches([np.arange(5), np.arange(5)], 2))

    def test_dict_variant(self):
        b0 = {"x": np.arange(4), "y": np.arange(4) * 10}
        b1 = {"x": np.arange(4, 8), "y": np.arange(4, 8) * 10}
        out = [{k: v.copy() for k, v in b.items()} for b in interleave_dict_batches([b0, b1], 2)]
        np.testing.assert_array_equal(out[0]["x"], [0, 1, 4, 5])
        np.testing.assert_array_equal(out[0]["y"], [0, 10, 40, 50])


class TestPackSequences:
    def test_round_trip_preserves_tokens_in_order(self):
        from dmlcloud_tpu.data import pack_sequences

        rng = np.random.RandomState(0)
        examples = [rng.randint(1, 100, size=n) for n in (5, 9, 3, 16, 7, 2)]
        rows = list(pack_sequences(examples, 16))
        # reconstruct: concatenation of non-pad tokens in row order == input order
        got = np.concatenate([r["tokens"][r["segment_ids"] > 0] for r in rows])
        want = np.concatenate(examples)
        np.testing.assert_array_equal(got, want)
        for r in rows:
            assert r["tokens"].shape == (16,) and r["segment_ids"].shape == (16,)
            # padding is exactly the seg==0 suffix
            nz = r["segment_ids"] > 0
            assert not nz[np.argmin(nz):].any() or nz.all()
            # segment ids are 1..k contiguous
            ids = r["segment_ids"][nz]
            assert ids.min() == 1 and set(np.unique(ids)) == set(range(1, ids.max() + 1))

    def test_long_example_splits_across_rows(self):
        from dmlcloud_tpu.data import pack_sequences

        rows = list(pack_sequences([np.arange(1, 23)], 8))  # 22 tokens over 8-rows
        assert len(rows) == 3
        got = np.concatenate([r["tokens"][r["segment_ids"] > 0] for r in rows])
        np.testing.assert_array_equal(got, np.arange(1, 23))
        # each split part is its own segment within its row
        assert rows[0]["segment_ids"].tolist() == [1] * 8
        assert rows[2]["segment_ids"].tolist() == [1] * 6 + [0, 0]

    def test_no_split_truncates(self):
        from dmlcloud_tpu.data import pack_sequences

        rows = list(pack_sequences([np.arange(1, 23), [7, 7]], 8, split_long=False))
        assert rows[0]["tokens"].tolist() == list(range(1, 9))  # truncated to 8
        assert rows[1]["tokens"][:2].tolist() == [7, 7]

    def test_feeds_model_contract(self, single_runtime):
        """Packed rows drive DecoderLM + lm_loss directly."""
        import jax
        import jax.numpy as jnp

        from dmlcloud_tpu.data import pack_sequences
        from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig, lm_loss

        rng = np.random.RandomState(1)
        rows = list(pack_sequences([rng.randint(1, 32, size=n) for n in (6, 10, 4)], 16))
        toks = np.stack([r["tokens"] for r in rows])
        segs = np.stack([r["segment_ids"] for r in rows])
        cfg = TransformerConfig(vocab_size=32, num_layers=1, num_heads=2, head_dim=8,
                                hidden_dim=16, mlp_dim=32, max_seq_len=16, dtype=jnp.float32)
        model = DecoderLM(cfg)
        params = model.init(jax.random.PRNGKey(0), jnp.asarray(toks))["params"]
        logits = model.apply({"params": params}, jnp.asarray(toks), segment_ids=jnp.asarray(segs))
        loss = lm_loss(logits, jnp.asarray(toks), segment_ids=jnp.asarray(segs))
        assert np.isfinite(float(loss))

    def test_whole_example_never_split_mid_row(self):
        """An example that fits an EMPTY row starts a fresh row instead of
        being severed across rows (splitting would break packed==unpacked)."""
        from dmlcloud_tpu.data import pack_sequences

        rows = list(pack_sequences([np.full(5, 1), np.full(6, 2)], 8))
        assert len(rows) == 2
        assert rows[0]["tokens"].tolist() == [1] * 5 + [0] * 3
        assert rows[1]["tokens"].tolist() == [2] * 6 + [0] * 2
        assert rows[1]["segment_ids"].tolist() == [1] * 6 + [0] * 2


class TestShuffleCombinator:
    def test_permutation_and_epoch_reshuffle(self):
        from dmlcloud_tpu.data import DataPipeline

        pipe = DataPipeline.from_source(list(range(50))).shuffle(buffer_size=8, seed=3)
        pipe.set_epoch(0)
        a = list(pipe)
        assert sorted(a) == list(range(50))  # a permutation, nothing lost
        assert a != list(range(50))  # and actually shuffled
        b = list(pipe)  # same epoch -> same order (deterministic)
        assert a == b
        pipe.set_epoch(1)
        c = list(pipe)
        assert sorted(c) == list(range(50)) and c != a  # reshuffled per epoch

    def test_locality_bounded_by_buffer(self):
        """An element cannot appear more than buffer_size positions EARLIER
        than its source position (reservoir semantics)."""
        from dmlcloud_tpu.data import DataPipeline

        n, buf = 200, 16
        pipe = DataPipeline.from_source(list(range(n))).shuffle(buffer_size=buf, seed=0)
        pipe.set_epoch(0)
        out = list(pipe)
        for pos, val in enumerate(out):
            assert pos >= val - (buf - 1)

    def test_buffer_one_is_identity(self):
        from dmlcloud_tpu.data import DataPipeline

        pipe = DataPipeline.from_source(list(range(10))).shuffle(buffer_size=1)
        pipe.set_epoch(0)
        assert list(pipe) == list(range(10))

    def test_rejects_bad_buffer(self):
        from dmlcloud_tpu.data import DataPipeline

        with pytest.raises(ValueError, match="buffer_size"):
            DataPipeline.from_source([1]).shuffle(buffer_size=0)

    def test_composes_with_batch(self):
        from dmlcloud_tpu.data import DataPipeline

        pipe = (
            DataPipeline.from_source([np.asarray([i]) for i in range(24)])
            .shuffle(buffer_size=6, seed=1)
            .batch(4)
        )
        pipe.set_epoch(0)
        batches = list(pipe)
        assert len(batches) == 6
        got = sorted(int(v) for b in batches for v in np.asarray(b).ravel())
        assert got == list(range(24))


def test_pack_sequences_fuzz():
    """Invariants over random workloads: every token preserved in order,
    rows exactly seq_len, segment ids 1..k with pad-0 suffix only."""
    from dmlcloud_tpu.data import pack_sequences

    rng = np.random.RandomState(11)
    for trial in range(60):
        seq_len = int(rng.randint(1, 33))
        n = int(rng.randint(0, 12))
        examples = [rng.randint(1, 1000, size=rng.randint(0, 3 * seq_len)) for _ in range(n)]
        rows = list(pack_sequences(examples, seq_len))
        got = [r["tokens"][r["segment_ids"] > 0] for r in rows]
        want = [e for e in examples if e.size]
        np.testing.assert_array_equal(
            np.concatenate(got) if got else np.empty(0, np.int32),
            np.concatenate(want) if want else np.empty(0, np.int32),
        )
        for r in rows:
            toks, segs = r["tokens"], r["segment_ids"]
            assert toks.shape == (seq_len,) and segs.shape == (seq_len,)
            nz = np.flatnonzero(segs)
            assert nz.size > 0  # no empty rows emitted
            assert nz[-1] == nz.size - 1  # padding only as a suffix
            ids = segs[: nz.size]
            # 1..k, non-decreasing, no skips
            assert ids[0] == 1 and (np.diff(ids) >= 0).all() and (np.diff(ids) <= 1).all()
            # pad slots carry token 0
            assert (toks[nz.size :] == 0).all()


def test_pack_combinator_composes():
    from dmlcloud_tpu.data import DataPipeline

    rng = np.random.RandomState(7)
    docs = [rng.randint(1, 100, size=n) for n in (5, 12, 3, 9, 20, 7)]
    pipe = DataPipeline.from_source(docs).pack(16).batch(2, drop_remainder=False,
        collate=lambda rows: {k: np.stack([r[k] for r in rows]) for k in rows[0]})
    pipe.set_epoch(0)
    batches = list(pipe)
    got = np.concatenate([
        b["tokens"][i][b["segment_ids"][i] > 0]
        for b in batches for i in range(b["tokens"].shape[0])
    ])
    np.testing.assert_array_equal(got, np.concatenate(docs))
    with pytest.raises(ValueError, match="seq_len"):
        DataPipeline.from_source(docs).pack(0)


def test_markov_tokens_learnable_structure():
    """The shared synthetic corpus: deterministic per seed, ~90% of tokens
    follow one fixed successor table (the structure a model can learn)."""
    from dmlcloud_tpu.data import markov_tokens

    a = markov_tokens(64, 32, 128, seed=3)
    b = markov_tokens(64, 32, 128, seed=3)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (32, 128) and a.dtype == np.int32
    assert a.min() >= 0 and a.max() < 64
    # recover the successor table from data and measure determinism
    follows = {}
    for row in a:
        for x, y in zip(row[:-1], row[1:]):
            follows.setdefault(int(x), []).append(int(y))
    frac = np.mean([
        np.mean([v == max(set(vs), key=vs.count) for v in vs])
        for vs in follows.values() if len(vs) >= 5
    ])
    assert 0.8 < frac < 0.99, frac


def test_device_iterator_abandonment_joins_prefetch_thread(mesh8):
    """The preemption drain path: abandoning a host-prefetching
    device_iterator mid-epoch must stop its background enqueue thread
    promptly (it would otherwise sit blocked on the full queue until
    interpreter exit, pinning queued batches and the source iterator)."""
    import threading
    import time as _time

    from dmlcloud_tpu.data.device import device_iterator

    def thread_alive():
        return any(
            t.name == "dml-host-prefetch" and t.is_alive() for t in threading.enumerate()
        )

    batches = ({"x": np.full((8, 2), i, np.float32)} for i in range(10_000))
    it = device_iterator(batches, mesh8, prefetch=1, host_prefetch=2)
    first = next(it)
    assert float(first["x"][0, 0]) == 0.0
    assert thread_alive()  # the producer is live and its queue is full

    it.close()  # consumer abandons the iterator mid-epoch

    deadline = _time.monotonic() + 5.0
    while thread_alive() and _time.monotonic() < deadline:
        _time.sleep(0.02)
    assert not thread_alive(), "host-prefetch thread did not exit after abandonment"


def test_feed_close_propagates_through_timed_feed(mesh8):
    """The stage's telemetry feed wrapper must forward close() to the
    device iterator (same drain-path promptness, one layer up)."""
    closed = []

    class Probe:
        def __iter__(self):
            try:
                for i in range(100):
                    yield {"x": np.full((8, 2), i, np.float32)}
            finally:
                closed.append(True)

    from dmlcloud_tpu.stage import TrainValStage

    stage = TrainValStage.__new__(TrainValStage)  # feed plumbing only
    stage._buckets_resolved = None
    stage._gp_data_wait_ns = 0

    class _P:
        mesh = mesh8

    stage.pipeline = _P()
    feed = stage._timed_feed(Probe())
    next(feed)
    feed.close()
    assert closed == [True]


class TestPackStream:
    """Streaming chunked packing (doc/data.md): per-chunk bit-identity with
    pack_sequences, live PackStats accounting, Python-fallback equality,
    and the replay-based resume cursor."""

    def _docs(self, n=37, seed=0):
        rng = np.random.RandomState(seed)
        return [rng.randint(1, 100, size=rng.randint(1, 20)).astype(np.int32) for _ in range(n)]

    def test_bit_identical_to_pack_sequences_per_chunk(self):
        from dmlcloud_tpu.data import DataPipeline, pack_sequences

        docs = self._docs()
        rows = list(DataPipeline.from_source(docs).pack_stream(16, chunk_docs=8))
        ref = []
        for c in range(0, len(docs), 8):
            ref.extend(pack_sequences(docs[c : c + 8], 16))
        assert len(rows) == len(ref)
        for a, b in zip(rows, ref):
            np.testing.assert_array_equal(a["tokens"], b["tokens"])
            np.testing.assert_array_equal(a["segment_ids"], b["segment_ids"])

    def test_python_fallback_matches_native_path(self, monkeypatch):
        """The two packers are interchangeable: forcing the Python path
        yields the exact same rows (trivially true where the native lib
        was never built — both runs fall back)."""
        from dmlcloud_tpu.data import DataPipeline
        from dmlcloud_tpu.native import pack as native_pack

        docs = self._docs(seed=3)
        native_rows = list(DataPipeline.from_source(docs).pack_stream(16, chunk_docs=5))
        monkeypatch.setattr(native_pack, "available", lambda: False)
        py_rows = list(DataPipeline.from_source(docs).pack_stream(16, chunk_docs=5))
        assert len(native_rows) == len(py_rows)
        for a, b in zip(native_rows, py_rows):
            np.testing.assert_array_equal(a["tokens"], b["tokens"])
            np.testing.assert_array_equal(a["segment_ids"], b["segment_ids"])

    def test_stats_account_padding_and_boundary(self):
        from dmlcloud_tpu.data import DataPipeline, pack_sequences

        docs = self._docs(seed=1)
        p = DataPipeline.from_source(docs).pack_stream(16, chunk_docs=8)
        rows = list(p)
        st = p.pack_stats
        assert st.docs == len(docs)
        assert st.chunks == -(-len(docs) // 8)
        assert st.rows == len(rows)
        assert st.slots == len(rows) * 16
        total_pad = sum(int((r["segment_ids"] == 0).sum()) for r in rows)
        assert st.pad_slots == total_pad
        assert st.tokens_placed == st.slots - total_pad
        assert st.tokens_in == sum(d.size for d in docs) == st.tokens_placed
        # boundary pad: the pad of each chunk's FINAL row, by construction
        boundary = 0
        for c in range(0, len(docs), 8):
            chunk_rows = list(pack_sequences(docs[c : c + 8], 16))
            boundary += int((chunk_rows[-1]["segment_ids"] == 0).sum())
        assert st.boundary_pad_slots == boundary
        assert 0.0 <= st.boundary_fraction <= st.pad_fraction < 1.0
        d = st.as_dict()
        assert d["pad_fraction"] == round(st.pad_slots / st.slots, 6)

    def test_empty_docs_are_skipped(self):
        from dmlcloud_tpu.data import DataPipeline

        rows = list(
            DataPipeline.from_source([np.zeros(0, np.int32), np.array([5, 6], np.int32)]).pack_stream(4)
        )
        assert len(rows) == 1
        assert rows[0]["tokens"].tolist() == [5, 6, 0, 0]

    def test_validation(self):
        from dmlcloud_tpu.data import DataPipeline

        with pytest.raises(ValueError):
            DataPipeline.from_source([]).pack_stream(0)
        with pytest.raises(ValueError):
            DataPipeline.from_source([]).pack_stream(8, chunk_docs=0)

    def test_composes_and_resumes_through_the_cursor(self, single_runtime):
        """pack_stream rides the PR-7 replay cursor: a chain interrupted
        mid-stream resumes bit-identically (every chunk re-derives)."""
        from dmlcloud_tpu.data import DataPipeline

        def build():
            p = DataPipeline.from_source(self._docs(n=40, seed=2))
            return p.shuffle(8, seed=3).pack_stream(16, chunk_docs=8).batch(
                2, collate=lambda b: np.stack([x["tokens"] for x in b])
            )

        ref = build()
        ref.set_epoch(1)
        full = list(ref)
        cut = 3
        interrupted = build()
        interrupted.set_epoch(1)
        it = iter(interrupted)
        for _ in range(cut):
            next(it)
        state = interrupted.state_dict()
        it.close()
        resumed = build()
        resumed.load_state_dict(state)
        tail = list(resumed)
        assert len(tail) == len(full) - cut
        for a, b in zip(tail, full[cut:]):
            np.testing.assert_array_equal(a, b)


class TestMixPipeline:
    """Deterministic weighted mixing (doc/data.md): pure-function draws,
    renormalize-on-exhaustion, and the mix-cursor resume contract."""

    def _mk(self, seed=5, weights=(3, 1)):
        from dmlcloud_tpu.data import DataPipeline

        return DataPipeline.mix(
            [
                DataPipeline.from_source(list(range(100, 130))),
                DataPipeline.from_source(list(range(200, 210))),
            ],
            weights=list(weights),
            seed=seed,
        )

    def test_same_seed_same_sequence(self):
        assert list(self._mk()) == list(self._mk())

    def test_different_seed_different_sequence(self):
        assert list(self._mk(seed=5)) != list(self._mk(seed=6))

    def test_drains_every_element_exactly_once(self):
        out = list(self._mk())
        assert sorted(out) == list(range(100, 130)) + list(range(200, 210))

    def test_weights_shape_the_head(self):
        """3:1 weights: the first draws favor source 0 accordingly (the
        sequence is deterministic, so the bound is stable)."""
        head = list(self._mk())[:24]
        frac0 = sum(1 for x in head if x < 200) / len(head)
        assert 0.55 <= frac0 <= 0.95

    def test_renormalizes_on_exhaustion_with_warning(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu"):
            out = list(self._mk(weights=(1, 8)))  # source 1 (10 elems) drains early
        assert any("renormalizing" in r.message for r in caplog.records)
        # after the short source drains, only source 0 remains
        drained_at = max(i for i, x in enumerate(out) if x >= 200)
        assert all(x < 200 for x in out[drained_at + 1 :])
        assert sorted(out) == list(range(100, 130)) + list(range(200, 210))

    def test_validation(self):
        from dmlcloud_tpu.data import DataPipeline

        with pytest.raises(ValueError):
            DataPipeline.mix([])
        with pytest.raises(ValueError):
            self._mk(weights=(1, 2, 3))
        with pytest.raises(ValueError):
            self._mk(weights=(1, 0))
        with pytest.raises(ValueError):
            self._mk(weights=(1, float("nan")))

    def test_len_sums_children(self):
        assert len(self._mk()) == 40

    def test_set_epoch_forwards_to_children(self):
        m = self._mk()
        m.set_epoch(7)
        assert all(s.epoch == 7 for s in m._sources)

    def test_resume_mid_stream_exact(self, single_runtime):
        """Cut at element k, save, restore into a FRESH mix: the tail is the
        uninterrupted sequence with 0 replayed and 0 skipped samples, and
        the resumed cursor continues from the restored offset."""
        full = list(self._mk())
        for cut in (1, 7, 33):  # before and after source-1 exhaustion
            m = self._mk()
            it = iter(m)
            head = [next(it) for _ in range(cut)]
            state = m.state_dict()
            assert state["kind"] == "mix" and state["global_offset"] == cut
            fresh = self._mk()
            fresh.load_state_dict(state)
            tail = list(fresh)
            assert head + tail == full
            assert fresh.state_dict()["global_offset"] == len(full)

    def test_resume_survives_failed_draws(self, single_runtime):
        """Draws that hit an exhausted source advance the draw counter but
        not the element cursor; the saved state carries both, so a resume
        lands on the exact same choice sequence."""
        m = self._mk(weights=(1, 8))
        it = iter(m)
        head = [next(it) for _ in range(20)]  # source 1 (10 elems) long gone
        state = m.state_dict()
        assert state["global_draws"] >= state["global_offset"]
        assert state["exhausted"] == [False, True]
        fresh = self._mk(weights=(1, 8))
        fresh.load_state_dict(state)
        assert head + list(fresh) == list(self._mk(weights=(1, 8)))

    def test_bad_state_rejected(self):
        m = self._mk()
        with pytest.raises(ValueError):
            m.load_state_dict({"v": 99, "kind": "mix"})
        with pytest.raises(ValueError):
            m.load_state_dict({"v": 1, "kind": "mix", "global_offset": 0, "global_draws": 0, "children": [{}]})

    def test_mix_feeds_pack_stream(self):
        """The composed production chain: mix -> pack_stream -> batch."""
        from dmlcloud_tpu.data import DataPipeline

        rng = np.random.RandomState(0)
        a = [rng.randint(1, 50, size=rng.randint(2, 12)).astype(np.int32) for _ in range(20)]
        b = [rng.randint(50, 99, size=rng.randint(2, 12)).astype(np.int32) for _ in range(20)]
        m = DataPipeline.mix(
            [DataPipeline.from_source(a), DataPipeline.from_source(b)], weights=[2, 1], seed=1
        )
        batches = list(
            m.pack_stream(16, chunk_docs=8).batch(
                2, drop_remainder=True,
                collate=lambda rows: {k: np.stack([r[k] for r in rows]) for k in ("tokens", "segment_ids")},
            )
        )
        assert batches and all(bt["tokens"].shape == (2, 16) for bt in batches)
        # the same seed reproduces the same batches
        m2 = DataPipeline.mix(
            [DataPipeline.from_source(a), DataPipeline.from_source(b)], weights=[2, 1], seed=1
        )
        batches2 = list(
            m2.pack_stream(16, chunk_docs=8).batch(
                2, drop_remainder=True,
                collate=lambda rows: {k: np.stack([r[k] for r in rows]) for k in ("tokens", "segment_ids")},
            )
        )
        for x, y in zip(batches, batches2):
            np.testing.assert_array_equal(x["tokens"], y["tokens"])


class TestPackedStreamLossIdentity:
    """Acceptance lock: the packed-stream loss is numerically identical to
    training the same documents unpacked — the segment-masked reference
    check (tier-1 twin of the slow test_packing.py suite)."""

    def test_loss_matches_unpacked_reference(self, single_runtime):
        import jax
        import jax.numpy as jnp

        from dmlcloud_tpu.data import DataPipeline
        from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig, lm_loss

        seq_len = 24
        rng = np.random.RandomState(0)
        docs = [rng.randint(1, 31, size=n).astype(np.int32) for n in (5, 9, 3, 7, 11, 4, 6)]
        rows = list(DataPipeline.from_source(docs).pack_stream(seq_len, chunk_docs=len(docs)))
        toks = jnp.asarray(np.stack([r["tokens"] for r in rows]))
        segs = jnp.asarray(np.stack([r["segment_ids"] for r in rows]))

        cfg = TransformerConfig(
            vocab_size=31, num_layers=2, num_heads=2, head_dim=8, hidden_dim=16,
            mlp_dim=32, max_seq_len=seq_len, dtype=jnp.float32,
        )
        model = DecoderLM(cfg)
        params = model.init(jax.random.PRNGKey(0), toks[:1])["params"]

        logits = model.apply({"params": params}, toks, segment_ids=segs)
        packed_loss = float(lm_loss(logits, toks, segment_ids=segs))

        # unpacked reference: each document alone, losses weighted by its
        # number of next-token targets (len - 1) — what _packed_mean counts
        num = den = 0.0
        for d in docs:
            dl = model.apply({"params": params}, jnp.asarray(d[None]))
            per_doc = float(lm_loss(dl, jnp.asarray(d[None])))
            num += per_doc * (d.size - 1)
            den += d.size - 1
        np.testing.assert_allclose(packed_loss, num / den, rtol=2e-5)
