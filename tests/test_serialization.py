"""JSON sidecar encoding: numeric pytrees must round-trip exactly through
to_jsonable -> json -> from_jsonable (dtype, shape, NaN included), and the
encoding must refuse non-string keys instead of corrupting silently."""

import json

import numpy as np
import pytest

from dmlcloud_tpu.metrics import MetricTracker, Reduction
from dmlcloud_tpu.utils.serialization import from_jsonable, to_jsonable


def _roundtrip(obj):
    return from_jsonable(json.loads(json.dumps(to_jsonable(obj))))


class TestRoundtrip:
    def test_scalars_and_none(self):
        obj = {"a": 1, "b": 2.5, "c": True, "d": None, "e": "text"}
        assert _roundtrip(obj) == obj

    def test_numpy_scalar_keeps_dtype(self):
        out = _roundtrip(np.float32(1.5))
        assert out == np.float32(1.5)
        assert out.dtype == np.float32

    def test_ndarray_keeps_dtype_and_shape(self):
        arr = np.arange(12, dtype=np.int16).reshape(3, 4)
        out = _roundtrip(arr)
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == arr.dtype

    def test_zero_dim_and_empty_arrays(self):
        zd = np.array(3.0)
        out = _roundtrip(zd)
        assert out.shape == () and out == 3.0
        empty = np.zeros((0, 3), dtype=np.float64)
        out = _roundtrip(empty)
        assert out.shape == (0, 3)

    def test_nan_and_inf(self):
        arr = np.array([np.nan, np.inf, -np.inf])
        out = _roundtrip(arr)
        assert np.isnan(out[0]) and np.isinf(out[1]) and out[2] == -np.inf

    def test_nested_lists_and_tuples(self):
        out = _roundtrip({"h": [(1, 2), None, np.float64(3.0)]})
        assert out["h"][0] == [1, 2]
        assert out["h"][1] is None
        assert out["h"][2] == 3.0

    def test_non_string_keys_rejected(self):
        with pytest.raises(TypeError, match="str keys"):
            to_jsonable({1: "x"})

    def test_unsupported_dtypes_raise_not_recurse(self):
        """Exotic numpy types must fail with a clear TypeError — not
        RecursionError (scalars) or un-dumpable output (object arrays)."""
        for bad in (np.complex64(1 + 2j), np.datetime64("2026-01-01"), np.array([object()])):
            with pytest.raises(TypeError, match="not JSON-encodable"):
                to_jsonable(bad)

    def test_tag_collision_rejected(self):
        with pytest.raises(TypeError, match="collides"):
            to_jsonable({"__ndarray__": [1]})


class TestTrackerStateJson:
    def test_tracker_state_roundtrips_through_json(self, single_runtime):
        t = MetricTracker()
        t.register_metric("loss", Reduction.MEAN)
        t.register_metric("note")
        t.track("loss", np.float32(0.5))
        t.track("note", 7)
        t.next_epoch()
        t.register_metric("acc", Reduction.MAX)
        t.track("acc", np.array([0.1, 0.9]))

        state = _roundtrip(t.state_dict())
        t2 = MetricTracker()
        t2.load_state_dict(state)
        assert t2.epoch == t.epoch
        assert t2["loss"] == [np.float32(0.5)]
        assert t2.reducers["acc"].reduction is Reduction.MAX
        # buffered (unreduced) values survive too
        assert len(t2.reducers["acc"].values) == 1
        t2.next_epoch()
        assert t2["acc"][-1] == pytest.approx(0.9)
