"""Serve observability plane (doc/observability.md): the metrics
registry's typed families and bounded cardinality, Prometheus exposition
round-trips through the strict parser, SLO burn-rate alerting off a fake
clock, the stdlib /metrics endpoint, the engine integration
(``metrics=True`` / ``slos=``), request-scoped trace linkage, the
flush-on-exit hardening, the observability CLI (``trace`` / ``top`` /
``timeline --by-request`` / the diag alert census), and analyze_trace's
serve mode with its v2 JSON schema."""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from dmlcloud_tpu.serve import SLO, MetricsServer, ServeEngine, SLOMonitor
from dmlcloud_tpu.telemetry import journal as journal_mod
from dmlcloud_tpu.telemetry.journal import (
    SpanJournal,
    linked_trace_report,
    load_journals,
    to_request_trace,
)
from dmlcloud_tpu.telemetry.metrics_registry import (
    ITL_BUCKETS,
    OVERFLOW_LABEL,
    TTFT_BUCKETS,
    MetricsRegistry,
    parse_prometheus_text,
    to_prometheus_text,
)


def _engine(model, params, **kw):
    kw.setdefault("num_blocks", 64)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_slots", 2)
    kw.setdefault("prefill_chunk", 8)
    return ServeEngine(model, params, **kw)


def _prompt(seed, n=12):
    return np.random.RandomState(seed).randint(0, 61, size=n).astype(np.int32)


def _flat_samples(fams):
    """parse_prometheus_text output flattened to {(name, labels): float}
    (the parser keeps sample values as raw strings)."""
    return {
        (n, tuple(sorted(l.items()))): float(v)
        for fam in fams.values() for n, l, v in fam["samples"]
    }


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_typed_families_and_snapshot_is_plain(self):
        reg = MetricsRegistry()
        c = reg.counter("req_total", "requests")
        c.inc()
        c.inc(2.5)
        with pytest.raises(ValueError):
            c.inc(-1)
        g = reg.gauge("depth", "queue depth")
        g.set(4)
        g.dec()
        h = reg.histogram("ttft_s", "ttft", buckets=TTFT_BUCKETS)
        h.observe(0.03)
        h.observe(100.0)  # lands in +Inf
        snap = reg.snapshot()
        json.dumps(snap)  # plain dicts, JSON-safe by contract
        assert snap["req_total"]["series"][0]["value"] == 3.5
        assert snap["depth"]["series"][0]["value"] == 3.0
        hs = snap["ttft_s"]["series"][0]
        assert hs["count"] == 2 and hs["buckets"][-1] == ["+Inf", 2]

    def test_reregister_same_family_kind_mismatch_raises(self):
        reg = MetricsRegistry()
        fam = reg.counter("x_total")
        assert reg.counter("x_total") is fam  # dedup, not a new family
        with pytest.raises(ValueError):
            reg.gauge("x_total")
        with pytest.raises(ValueError):
            reg.counter("x_total", labels=("status",))  # label-set mismatch

    def test_labels_exact_set_enforced(self):
        reg = MetricsRegistry()
        fam = reg.counter("t_total", labels=("status",))
        fam.labels(status="ok").inc()
        with pytest.raises(ValueError):
            fam.labels(tenant="x")
        with pytest.raises(ValueError):
            fam.labels(status="ok", tenant="x")

    def test_cardinality_overflow_collapses(self):
        reg = MetricsRegistry()
        fam = reg.counter("per_rid_total", labels=("rid",), max_series=2)
        fam.labels(rid="a").inc()
        fam.labels(rid="b").inc()
        for rid in ("c", "d", "e"):  # past the cap: ONE overflow series
            fam.labels(rid=rid).inc()
        assert fam.overflows == 3
        snap = reg.snapshot()["per_rid_total"]
        labels = [s["labels"]["rid"] for s in snap["series"]]
        assert labels.count(OVERFLOW_LABEL) == 1
        overflow = next(
            s for s in snap["series"] if s["labels"]["rid"] == OVERFLOW_LABEL
        )
        assert overflow["value"] == 3.0
        assert snap["overflows"] == 3

    def test_invalid_names_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("bad name")
        with pytest.raises(ValueError):
            reg.counter("ok_total", labels=("bad-label",))
        with pytest.raises(ValueError):
            reg.counter("ok_total", labels=("__reserved",))
        with pytest.raises(ValueError):
            reg.histogram("h", buckets=(2.0, 1.0))  # unsorted buckets

    def test_prometheus_round_trip(self):
        reg = MetricsRegistry()
        reg.counter("dml_req_total", "requests", labels=("status",)).labels(
            status="ok"
        ).inc(7)
        reg.gauge("dml_depth", "depth").set(3)
        h = reg.histogram("dml_ttft_seconds", "ttft", buckets=ITL_BUCKETS)
        h.observe(0.002)
        h.observe(0.02)
        text = reg.snapshot()
        page = to_prometheus_text(text)
        fams = parse_prometheus_text(page)
        assert fams["dml_req_total"]["type"] == "counter"
        assert fams["dml_depth"]["type"] == "gauge"
        assert fams["dml_ttft_seconds"]["type"] == "histogram"
        samples = _flat_samples(fams)
        assert samples[("dml_req_total", (("status", "ok"),))] == 7.0
        hist = fams["dml_ttft_seconds"]["samples"]
        counts = {n for n, _, _ in hist}
        assert {"dml_ttft_seconds_bucket", "dml_ttft_seconds_sum",
                "dml_ttft_seconds_count"} <= counts
        inf = next(
            float(v) for n, l, v in hist
            if n == "dml_ttft_seconds_bucket" and l.get("le") == "+Inf"
        )
        total = next(
            float(v) for n, _, v in hist if n == "dml_ttft_seconds_count"
        )
        assert inf == total == 2.0

    def test_multi_snapshot_merge_tags_extra_labels(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("dml_req_total", "requests").inc(1)
        b.counter("dml_req_total", "requests").inc(2)
        page = to_prometheus_text(
            (a.snapshot(), {"replica": "r0"}), (b.snapshot(), {"replica": "r1"})
        )
        # one HELP/TYPE header for the merged family, two tagged series
        assert page.count("# TYPE dml_req_total") == 1
        fams = parse_prometheus_text(page)
        by_replica = {
            l["replica"]: float(v) for _, l, v in fams["dml_req_total"]["samples"]
        }
        assert by_replica == {"r0": 1.0, "r1": 2.0}
        # a kind collision across snapshots is a hard error
        g = MetricsRegistry()
        g.gauge("dml_req_total").set(1)
        with pytest.raises(ValueError):
            to_prometheus_text(a.snapshot(), g.snapshot())

    def test_save_never_raises_and_close_is_idempotent(self, tmp_path):
        path = tmp_path / "metrics.json"
        reg = MetricsRegistry(save_path=path)
        reg.counter("x_total").inc(5)
        assert reg.save() == str(path)
        assert json.loads(path.read_text())["x_total"]["series"][0]["value"] == 5.0
        reg.close()
        reg.close()  # idempotent
        # a doomed path is swallowed, not raised (metrics must not kill serving)
        assert MetricsRegistry().save(tmp_path / "no" / "such" / "dir" / "m.json") is None


# ---------------------------------------------------------------------------
# SLO monitor (fake clock — no sleeps anywhere)
# ---------------------------------------------------------------------------


def _slo_latency(**kw):
    kw.setdefault("ttft_p99_s", 0.1)
    kw.setdefault("good_fraction", 0.5)
    kw.setdefault("window_s", 10.0)
    kw.setdefault("fast_window_s", 1.0)
    kw.setdefault("burn_threshold", 1.5)
    return SLO("lat", **kw)


class TestSLOMonitor:
    def test_declaration_validation(self):
        with pytest.raises(ValueError):
            SLO("empty")  # no objective at all
        with pytest.raises(ValueError):
            SLO("bad", ttft_p99_s=-1)
        with pytest.raises(ValueError):
            SLO("bad", availability=1.5)
        with pytest.raises(ValueError):
            SLO("bad", ttft_p99_s=1.0, window_s=1.0, fast_window_s=2.0)
        with pytest.raises(ValueError):
            SLOMonitor([_slo_latency(), _slo_latency()])  # duplicate names

    def test_multi_window_burn_fires_once_then_rearms(self):
        mon = SLOMonitor([_slo_latency()], clock=lambda: 0.0)
        # sustained breach: every request misses the 100ms target across
        # both windows
        for i in range(20):
            mon.record_ttft(None, 1.0, now=i * 0.05)
        fired = mon.evaluate(now=1.0)
        assert [a["slo"] for a in fired] == ["lat"]
        assert fired[0]["part"] == "ttft"
        assert fired[0]["burn_fast"] >= 1.5 and fired[0]["burn_slow"] >= 1.5
        # still burning: the latch holds, no second page for the same breach
        mon.record_ttft(None, 1.0, now=1.2)
        assert mon.evaluate(now=1.3) == []
        # recovery: the fast window fills with good requests and re-arms
        for i in range(20):
            mon.record_ttft(None, 0.01, now=3.0 + i * 0.04)
        assert mon.evaluate(now=3.9) == []
        # a fresh sustained breach fires a SECOND alert
        for i in range(40):
            mon.record_ttft(None, 1.0, now=5.0 + i * 0.1)
        assert len(mon.evaluate(now=9.0)) == 1
        assert len(mon.alerts) == 2

    def test_one_slow_request_does_not_page(self):
        mon = SLOMonitor([_slo_latency()], clock=lambda: 0.0)
        # plenty of good traffic in the slow window, ONE bad request
        for i in range(50):
            mon.record_ttft(None, 0.01, now=i * 0.1)
        mon.record_ttft(None, 5.0, now=4.95)
        assert mon.evaluate(now=5.0) == []  # slow window is not burning

    def test_cancelled_spends_no_budget(self):
        slo = SLO("avail", availability=0.9, window_s=10.0, fast_window_s=1.0,
                  burn_threshold=1.0)
        mon = SLOMonitor([slo], clock=lambda: 0.0)
        for i in range(30):
            mon.record_terminal(None, "cancelled", now=i * 0.1)
        assert mon.evaluate(now=3.0) == []
        assert mon.status(now=3.0)["objectives"]["avail"]["availability"]["n"] == 0
        # errors DO spend it
        for i in range(10):
            mon.record_terminal(None, "error", now=4.0 + i * 0.05)
        assert len(mon.evaluate(now=4.5)) == 1

    def test_tenant_scoping(self):
        slo = SLO("gold", tenant="gold", ttft_p99_s=0.1, good_fraction=0.5,
                  window_s=10.0, fast_window_s=1.0, burn_threshold=1.0)
        mon = SLOMonitor([slo], clock=lambda: 0.0)
        for i in range(20):  # the breach is entirely another tenant's
            mon.record_ttft("bronze", 9.0, now=i * 0.05)
        assert mon.evaluate(now=1.0) == []
        for i in range(20):
            mon.record_ttft("gold", 9.0, now=2.0 + i * 0.05)
        assert len(mon.evaluate(now=3.0)) == 1

    def test_alert_journals_slo_alert_span(self, tmp_path):
        j = SpanJournal(tmp_path, rank=0)
        journal_mod.activate(j)
        try:
            mon = SLOMonitor([_slo_latency()], clock=lambda: 0.0)
            for i in range(20):
                mon.record_ttft(None, 1.0, now=i * 0.05)
            assert mon.evaluate(now=1.0)
        finally:
            journal_mod.deactivate()
        spans = [r for r in j.tail(64) if r["kind"] == "slo_alert"]
        assert len(spans) == 1
        assert spans[0]["slo"] == "lat" and spans[0]["part"] == "ttft"
        assert spans[0]["burn_fast"] >= 1.5

    def test_status_scorecard(self):
        mon = SLOMonitor([_slo_latency()], clock=lambda: 2.0)
        for i in range(10):
            mon.record_ttft(None, 0.02, now=1.0 + i * 0.01)
        st = mon.status()  # falls back to the injected clock
        ttft = st["objectives"]["lat"]["ttft"]
        assert ttft["n"] == 10 and ttft["target_p99_s"] == 0.1
        assert ttft["observed_p99_s"] == pytest.approx(0.02, abs=1e-6)
        assert st["alerts"] == 0


# ---------------------------------------------------------------------------
# /metrics HTTP endpoint
# ---------------------------------------------------------------------------


class TestMetricsServer:
    def test_scrape_404_and_500(self):
        reg = MetricsRegistry()
        reg.counter("dml_up_total").inc()
        with MetricsServer(lambda: to_prometheus_text(reg.snapshot())) as srv:
            with urllib.request.urlopen(srv.url, timeout=5) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/plain")
                body = resp.read().decode()
            assert parse_prometheus_text(body)["dml_up_total"]["type"] == "counter"
            with pytest.raises(urllib.error.HTTPError) as e404:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=5)
            assert e404.value.code == 404
        # a raising source answers 500 — it never kills the serving process
        def boom():
            raise RuntimeError("registry on fire")

        with MetricsServer(boom) as srv:
            with pytest.raises(urllib.error.HTTPError) as e500:
                urllib.request.urlopen(srv.url, timeout=5)
            assert e500.value.code == 500
            assert "registry on fire" in e500.value.read().decode()

    def test_port_requires_start(self):
        srv = MetricsServer(lambda: "")
        with pytest.raises(RuntimeError):
            srv.port


# ---------------------------------------------------------------------------
# engine integration: metrics=True / slos=, trace linkage
# ---------------------------------------------------------------------------


class TestEngineObservability:
    def test_metrics_slo_and_traces_plumbed(self, tiny_model, tmp_path):
        model, params = tiny_model
        j = SpanJournal(tmp_path / "telemetry", rank=0)
        journal_mod.activate(j)
        try:
            engine = _engine(
                model, params, metrics=True,
                slos=[SLO("loose", ttft_p99_s=1e9, availability=0.5)],
            )
            a = engine.submit(_prompt(0), max_new_tokens=6, tenant="gold")
            b = engine.submit(_prompt(1), max_new_tokens=4)
            engine.run()
        finally:
            journal_mod.deactivate()
        assert engine.status(a) == "ok" and engine.status(b) == "ok"

        # exposition parses as strict Prometheus text and carries the
        # schema-locked serve families with the right values
        fams = parse_prometheus_text(engine.metrics_text())
        flat = _flat_samples(fams)
        assert flat[("dml_serve_requests_total", ())] == 2.0
        assert flat[("dml_serve_terminal_total", (("status", "ok"),))] == 2.0
        assert flat[("dml_serve_tokens_total", ())] == 10.0
        assert flat[("dml_serve_ttft_seconds_count", ())] == 2.0
        assert flat[("dml_serve_itl_seconds_count", ())] > 0
        assert flat[("dml_serve_active_requests", ())] == 0.0
        for fam in ("dml_serve_kv_blocks_free", "dml_serve_queue_depth",
                    "dml_serve_decode_batch_size"):
            assert fam in fams

        # the ledger summary surfaces the SLO scorecard
        slo = engine.ledger.summary()["slo"]["objectives"]["loose"]
        assert slo["ttft"]["n"] == 2
        assert slo["availability"]["observed"] == 1.0

        # every span either carries this request's trace id or lists it:
        # one causal trace per request, zero orphans
        report = linked_trace_report(j.tail(10 ** 6))
        assert report["orphans"] == []
        assert {f"tr-{a}", f"tr-{b}"} <= set(report["traces"])
        kinds_a = {r["kind"] for r in report["traces"][f"tr-{a}"]}
        assert {"queue_wait", "admission", "prefill", "decode_batch"} <= kinds_a
        assert report["statuses"][f"tr-{a}"] is None  # no fault touched it
        adm = next(
            r for r in report["traces"][f"tr-{a}"] if r["kind"] == "admission"
        )
        assert adm["tenant"] == "gold"

    def test_fault_stamps_trace_with_terminal_status(self, tiny_model, tmp_path):
        model, params = tiny_model
        j = SpanJournal(tmp_path / "telemetry", rank=0)
        journal_mod.activate(j)
        try:
            engine = _engine(model, params, metrics=True)
            boom = {"armed": True}

            def injector(point, seqs):
                if point == "decode" and boom["armed"]:
                    boom["armed"] = False
                    raise RuntimeError("injected decode fault")

            engine.fault_injector = injector
            rid = engine.submit(_prompt(2), max_new_tokens=6)
            engine.run()
        finally:
            journal_mod.deactivate()
        assert engine.status(rid) == "error"
        report = linked_trace_report(j.tail(10 ** 6))
        assert report["orphans"] == []
        assert report["statuses"][f"tr-{rid}"] == "error"
        flat = _flat_samples(parse_prometheus_text(engine.metrics_text()))
        assert flat[("dml_serve_terminal_total", (("status", "error"),))] == 1.0

    def test_drain_verdict_counts_slo_alerts(self, tiny_model, tmp_path):
        from dmlcloud_tpu.checkpoint import read_requeue_verdict

        model, params = tiny_model
        engine = _engine(
            model, params, run_dir=str(tmp_path),
            slos=[SLO("loose", ttft_p99_s=1e9)],
        )
        engine.submit(_prompt(3), max_new_tokens=4)
        engine.run()
        engine.drain(reason="test")
        verdict = read_requeue_verdict(str(tmp_path))
        assert verdict["serve"]["slo_alerts"] == 0


# ---------------------------------------------------------------------------
# flush-on-exit hardening (subprocess — the process exits WITHOUT close())
# ---------------------------------------------------------------------------


_EXIT_CHILD = """
import sys
sys.argv = ["flush_child"]
from dmlcloud_tpu.telemetry import journal as journal_mod
from dmlcloud_tpu.telemetry.journal import SpanJournal
from dmlcloud_tpu.telemetry.metrics_registry import MetricsRegistry

run_dir = {run_dir!r}
j = SpanJournal(run_dir, rank=0, flush_interval=3600.0).start()
journal_mod.activate(j)
t = journal_mod.now()
journal_mod.emit("queue_wait", t, t + 0.001, request=0, trace="tr-0")
reg = MetricsRegistry(save_path=run_dir + "/metrics.json")
reg.counter("dml_exit_total").inc(3)
# no close(), no deactivate(): atexit hooks must flush both
"""


class TestFlushOnExit:
    def test_journal_and_registry_survive_unclean_exit(self, tmp_path):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, "-c", _EXIT_CHILD.format(run_dir=str(tmp_path))],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        records = load_journals(tmp_path)
        assert [r["kind"] for r in records] == ["queue_wait"]
        assert records[0]["trace"] == "tr-0"
        snap = json.loads((tmp_path / "metrics.json").read_text())
        assert snap["dml_exit_total"]["series"][0]["value"] == 3.0


# ---------------------------------------------------------------------------
# CLI: trace / top / timeline --by-request / diag alert census
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def obs_run(tiny_model, tmp_path_factory):
    """One observability-armed serve run shared by the CLI tests: journal
    + a saved registry snapshot under <run>/telemetry/, two requests, one
    hand-appended slo_alert record for the diag census."""
    model, params = tiny_model
    run_dir = tmp_path_factory.mktemp("obs_run")
    tdir = run_dir / "telemetry"
    j = SpanJournal(tdir, rank=0)
    journal_mod.activate(j)
    try:
        engine = _engine(model, params, metrics=True)
        engine.submit(_prompt(0), max_new_tokens=6, tenant="gold")
        engine.submit(_prompt(1), max_new_tokens=4)
        engine.run()
        snap = engine.metrics_snapshot()
    finally:
        journal_mod.deactivate()
        j.close()
    (tdir / "metrics.json").write_text(json.dumps(snap))
    alert = {
        "v": 1, "kind": "slo_alert", "label": "lat", "ts": journal_mod.now(),
        "dur": 1.0, "rank": 0, "tid": "main", "slo": "lat", "part": "ttft",
        "tenant": "", "burn_fast": 3.2, "burn_slow": 2.1,
    }
    with open(tdir / "journal-rank0.jsonl", "a", encoding="utf-8") as f:
        f.write(json.dumps(alert) + "\n")
    return str(run_dir)


class TestObservabilityCLI:
    def test_trace_cli_json(self, obs_run, capsys):
        from dmlcloud_tpu.__main__ import main

        assert main(["trace", obs_run, "--rid", "0", "--json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["trace"] == "tr-0"
        assert out["status"] is None  # no fault stamped this trace
        b = out["ttft_breakdown"]
        assert b["ttft_s"] is not None and b["ttft_s"] > 0
        assert b["queue_s"] >= 0 and b["prefill_s"] > 0
        assert {s["kind"] for s in out["spans"]} >= {"admission", "prefill"}

    def test_trace_cli_table_and_unknown_rid(self, obs_run, capsys):
        from dmlcloud_tpu.__main__ import main

        assert main(["trace", obs_run, "--rid", "0"]) == 0
        out = capsys.readouterr().out
        assert "TTFT" in out and "prefill" in out
        assert main(["trace", obs_run, "--rid", "99"]) == 1
        assert "tr-99" in capsys.readouterr().err

    def test_timeline_by_request(self, obs_run, tmp_path, capsys):
        from dmlcloud_tpu.__main__ import main

        out_path = tmp_path / "trace.json"
        assert main(["timeline", obs_run, "--by-request", "-o", str(out_path)]) == 0
        trace = json.loads(out_path.read_text())
        names = {e.get("name") for e in trace["traceEvents"]}
        # one thread-name metadata event per request track
        assert any(n == "thread_name" for n in names)
        records = load_journals(obs_run)
        tracks = to_request_trace(records)
        assert tracks["traceEvents"]  # importable helper agrees with the CLI

    def test_top_once_renders_a_frame(self, obs_run, capsys):
        from dmlcloud_tpu.__main__ import main

        assert main(["top", obs_run, "--once"]) == 0
        out = capsys.readouterr().out
        assert "requests" in out and "kv pool" in out

    def test_top_url_scrapes_prometheus(self, obs_run, capsys):
        from dmlcloud_tpu.__main__ import main

        snap = json.loads(
            open(os.path.join(obs_run, "telemetry", "metrics.json")).read()
        )
        with MetricsServer(lambda: to_prometheus_text(snap)) as srv:
            assert main(["top", "--url", srv.url, "--once"]) == 0
        assert "requests" in capsys.readouterr().out

    def test_diag_run_counts_slo_alerts(self, obs_run, capsys):
        from dmlcloud_tpu.__main__ import main

        assert main(["diag", "--json", "--run", obs_run]) == 0
        out = json.loads(capsys.readouterr().out)
        census = out["telemetry"]["slo_alerts"]
        assert census["count"] == 1
        assert census["by_objective"] == {"lat/ttft": 1}
        assert census["max_burn_fast"] == pytest.approx(3.2)


# ---------------------------------------------------------------------------
# analyze_trace: serve mode + v2 JSON schema
# ---------------------------------------------------------------------------


def _load_analyze_trace():
    import importlib.util
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "scripts" / "analyze_trace.py"
    if not path.is_file():
        pytest.skip("scripts/ not present next to the package")
    spec = importlib.util.spec_from_file_location("_analyze_trace_obs_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _synthetic_serve_journal(tmp_path):
    def rec(kind, ts, dur, **attrs):
        return {"v": 1, "kind": kind, "label": None, "ts": ts, "dur": dur,
                "rank": 0, "tid": "main", **attrs}

    records = [
        rec("queue_wait", 0.00, 0.01, request=0, trace="tr-0"),
        rec("admission", 0.01, 0.01, request=0, trace="tr-0", tenant="hot"),
        rec("prefill", 0.02, 0.03, request=0, trace="tr-0"),
        rec("decode_batch", 0.05, 0.01, traces=["tr-0"]),
        rec("decode_batch", 0.07, 0.01, traces=["tr-0", "tr-1"]),
        rec("queue_wait", 0.03, 0.01, request=1, trace="tr-1"),
        rec("admission", 0.04, 0.01, request=1, trace="tr-1", tenant="cold"),
        rec("prefill", 0.05, 0.02, request=1, trace="tr-1"),
        rec("fault", 0.09, 0.0, request=1, trace="tr-1", status="error"),
    ]
    with open(tmp_path / "journal-rank0.jsonl", "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


class TestAnalyzeTraceServe:
    def test_serve_mode_json_schema_v2(self, tmp_path, capsys):
        mod = _load_analyze_trace()
        _synthetic_serve_journal(tmp_path)
        assert mod.main([str(tmp_path), "--json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["version"] == 2
        s = out["serve"]
        assert s["requests"] == 2 and s["orphan_spans"] == 0
        assert s["statuses"] == {"ok": 1, "error": 1}
        assert s["ttft_ms"]["n"] == 2
        assert s["ttft_ms"]["p50"] == pytest.approx(50.0, abs=5.0)
        assert set(s["tenants"]) == {"hot", "cold"}

    def test_tenant_filter(self, tmp_path, capsys):
        mod = _load_analyze_trace()
        _synthetic_serve_journal(tmp_path)
        assert mod.main([str(tmp_path), "--json", "--tenant", "hot"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["serve"]["requests"] == 1
        assert set(out["serve"]["tenants"]) == {"hot"}

    def test_table_output_and_tenant_without_journals(self, tmp_path, capsys):
        mod = _load_analyze_trace()
        _synthetic_serve_journal(tmp_path)
        assert mod.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "ttft_ms" in out and "2 requests" in out
        # --tenant is meaningless on a roofline (xplane) directory
        empty = tmp_path / "empty"
        empty.mkdir()
        assert mod.main([str(empty), "--tenant", "hot"]) == 2
