"""Gradient accumulation: the lax.scan microbatch loop inside the compiled
step must be numerically equivalent to one full-batch step (mean losses over
equal-size microbatches average to the full-batch mean gradient), thread
auxiliary state through the scan, and reject indivisible batch dims.

The reference has no accumulation; this is TPU-side scope (one dispatch per
optimizer step regardless of microbatch count).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dmlcloud_tpu import TrainingPipeline, TrainValStage


def _linear_stage(accum, batches=None):
    class LinearStage(TrainValStage):
        def pre_stage(self):
            rng = np.random.RandomState(0)
            xs = rng.randn(16, 10).astype(np.float32)
            ys = (xs @ rng.randn(10, 1)).astype(np.float32)
            data = batches if batches is not None else [{"x": xs, "y": ys}]
            self.pipeline.register_dataset("train", data, verbose=False)

            params = {"w": jnp.zeros((10, 1)), "b": jnp.zeros((1,))}

            def apply_fn(params, x):
                return x @ params["w"] + params["b"]

            self.pipeline.register_model("linear", apply_fn=apply_fn, params=params, verbose=False)
            self.pipeline.register_optimizer("sgd", optax.sgd(0.05))

        def gradient_accumulation(self):
            return accum

        def step(self, state, batch):
            pred = state.apply_fn(state.params, batch["x"])
            loss = jnp.mean((pred - batch["y"]) ** 2)
            # a real metrics dict so the fp32 metric accumulators are exercised
            return loss, {"mae": jnp.mean(jnp.abs(pred - batch["y"]))}

        def val_epoch(self):
            pass

    return LinearStage()


def _run(accum, batches=None):
    pipeline = TrainingPipeline({"seed": 0}, name=f"accum{accum}")
    stage = _linear_stage(accum, batches)
    pipeline.append_stage(stage, max_epochs=1)
    pipeline.run()
    return stage


def test_accumulated_step_matches_full_batch(single_runtime):
    full = _run(1)
    acc = _run(4)
    np.testing.assert_allclose(
        np.asarray(acc.state.params["w"]), np.asarray(full.state.params["w"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(acc.state.params["b"]), np.asarray(full.state.params["b"]), rtol=1e-5
    )
    # losses agree too (mean over microbatch means == full-batch mean for MSE)
    assert abs(acc.pipeline.tracker["train/loss"][0] - full.pipeline.tracker["train/loss"][0]) < 1e-5
    # user metrics went through the fp32 accumulators and still match
    assert abs(acc.pipeline.tracker["train/mae"][0] - full.pipeline.tracker["train/mae"][0]) < 1e-5
    # one optimizer step, not four
    assert int(jax.device_get(acc.state.step)) == 1


def test_accumulation_threads_extras(single_runtime):
    """Aux state written by the step must come from the LAST microbatch."""

    class ExtrasStage(TrainValStage):
        def pre_stage(self):
            xs = np.arange(8, dtype=np.float32).reshape(8, 1)
            self.pipeline.register_dataset("train", [{"x": xs}], verbose=False)

            def apply_fn(params, x):
                return x * params["w"]

            # flax-style variables dict: "params" is trained, other
            # collections become state.extras (like BatchNorm batch_stats)
            self.pipeline.register_model(
                "m",
                apply_fn=apply_fn,
                params={"params": {"w": jnp.ones(())}, "aux": {"seen": jnp.zeros(())}},
                verbose=False,
            )
            self.pipeline.register_optimizer("sgd", optax.sgd(0.0))

        def gradient_accumulation(self):
            return 4

        def step(self, state, batch):
            loss = jnp.mean(state.apply_fn(state.params, batch["x"]) ** 2)
            # extras track the max input this microbatch saw, plus the carry
            seen = jnp.maximum(state.extras["aux"]["seen"], jnp.max(batch["x"]))
            return loss, {}, {"aux": {"seen": seen}}

        def val_epoch(self):
            pass

    pipeline = TrainingPipeline(name="accum-extras")
    stage = ExtrasStage()
    pipeline.append_stage(stage, max_epochs=1)
    pipeline.run()
    # the carry crossed all 4 microbatches: global max, not last slice's local max
    assert float(jax.device_get(stage.state.extras["aux"]["seen"])) == 7.0


def test_accumulation_rejects_indivisible_batch(single_runtime):
    # batch of 16 shards over the 8-device mesh but 16 % 3 != 0
    with pytest.raises(ValueError, match="must divide"):
        _run(3)
