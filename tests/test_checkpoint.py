"""Checkpoint dir contract, Slurm rediscovery, config round-trip, and Orbax
tensor-state save/restore (the capability the reference leaves to user hooks)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.checkpoint import (
    CheckpointDir,
    find_slurm_checkpoint,
    generate_checkpoint_path,
    generate_id,
)


def test_generate_id_urlsafe():
    i = generate_id(12)
    assert len(i) == 12
    assert i.isalnum()


def test_generate_checkpoint_path(tmp_path):
    p = generate_checkpoint_path(tmp_path, "exp/1")
    assert p.parent == tmp_path
    assert p.name.startswith("exp_1-")  # slash sanitized
    assert p != generate_checkpoint_path(tmp_path, "exp/1")


def test_create_and_validity(tmp_path):
    ckpt = CheckpointDir(tmp_path / "run")
    assert not ckpt.is_valid
    ckpt.create()
    assert ckpt.is_valid
    assert ckpt.log_file.exists()
    with pytest.raises(RuntimeError):
        ckpt.create()


def test_config_roundtrip(tmp_path):
    ckpt = CheckpointDir(tmp_path / "run")
    ckpt.create()
    ckpt.save_config({"lr": 0.1, "model": {"depth": 3}})
    cfg = ckpt.load_config()
    assert cfg.lr == 0.1
    assert cfg.model.depth == 3


def test_slurm_rediscovery(tmp_path, monkeypatch):
    monkeypatch.setenv("SLURM_JOB_ID", "4242")
    ckpt = CheckpointDir(tmp_path / "run-a")
    ckpt.create()
    assert ckpt.slurm_job_id == "4242"

    found = find_slurm_checkpoint(tmp_path)
    assert found == ckpt.path

    monkeypatch.setenv("SLURM_JOB_ID", "9999")
    assert find_slurm_checkpoint(tmp_path) is None


def test_orbax_state_roundtrip(tmp_path, single_runtime):
    ckpt = CheckpointDir(tmp_path / "run")
    ckpt.create()
    state = {"w": jnp.arange(8.0), "step": jnp.int32(5)}
    ckpt.save_state(0, state)
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 0

    restored = ckpt.restore_state(template=state)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(8.0))
    assert int(restored["step"]) == 5
    ckpt.close()


class TestRemotePaths:
    """gs:// URIs must survive to the storage backend intact (a plain
    ``Path.resolve()`` would mangle ``gs://bucket`` into ``gs:/bucket``
    before Orbax or gfile ever saw it)."""

    def test_uri_not_mangled(self):
        ckpt = CheckpointDir("gs://bucket/run")
        assert str(ckpt) == "gs://bucket/run"
        assert str(ckpt.config_file) == "gs://bucket/run/config.yaml"
        assert str(ckpt.state_dir) == "gs://bucket/run/state"

    def test_generate_path_keeps_scheme(self):
        p = generate_checkpoint_path("gs://bucket/experiments", "exp")
        assert str(p).startswith("gs://bucket/experiments/exp-")

    def test_local_paths_still_absolutised(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        ckpt = CheckpointDir("relative/run")
        assert str(ckpt) == str(tmp_path / "relative" / "run")

    def _redirect(self, tmp_path):
        """Mock the epath backend so gs://test-bucket maps onto tmp_path —
        exercises the real CheckpointDir code against the gfile API surface
        without network access."""
        import contextlib
        import os
        from unittest import mock

        from etils.epath import gpath, testing as epath_testing

        prefix = "gs://test-bucket"

        def tr(p):
            return os.fspath(p).replace(prefix, str(tmp_path))

        def passthrough(original_fn, path, *args, **kwargs):
            return original_fn(tr(path), *args, **kwargs)

        ops = [
            "exists", "isdir", "listdir", "mkdir", "makedirs", "open",
            "glob", "remove", "rename", "replace", "stat", "walk", "copy",
        ]
        stack = contextlib.ExitStack()
        # epath routes URI schemes straight to the tensorflow backend when TF
        # is importable, bypassing the mocked backend table — disable that
        # preference so the mock sees the gs:// calls
        stack.enter_context(mock.patch.object(gpath, "_is_tf_installed", lambda: False))
        stack.enter_context(epath_testing.mock_epath(**{op: passthrough for op in ops}))
        return stack

    def test_contract_files_on_mocked_gcs(self, tmp_path):
        from dmlcloud_tpu.utils.config import Config

        with self._redirect(tmp_path):
            ckpt = CheckpointDir("gs://test-bucket/run1")
            assert not ckpt.is_valid
            ckpt.create()
            assert ckpt.is_valid
            assert (tmp_path / "run1" / ".dmlcloud_tpu").exists()  # landed "remotely"
            ckpt.save_config(Config({"lr": 0.1, "model": {"width": 8}}))
            loaded = ckpt.load_config()
            assert loaded.get("lr") == 0.1
            assert loaded.get("model").get("width") == 8

    def test_atomic_write_text_remote_branch(self, tmp_path):
        from dmlcloud_tpu.checkpoint import atomic_write_text, as_run_path

        with self._redirect(tmp_path):
            target = as_run_path("gs://test-bucket/meta.json")
            atomic_write_text(target, '{"epoch": 3}')
            assert (tmp_path / "meta.json").read_text() == '{"epoch": 3}'
        # local branch goes through tmp+rename (no stray tmp file left)
        local = as_run_path(str(tmp_path / "local.json"))
        atomic_write_text(local, "x")
        assert (tmp_path / "local.json").read_text() == "x"
        assert not list(tmp_path.glob(".*.tmp"))


def test_normalize_opt_distinguishes_closures():
    """Two lambdas from the same source line with different captured values
    must normalize differently (a changed best-metric name must trip the
    changed-options guard, not silently pass)."""
    from dmlcloud_tpu.checkpoint import _normalize_opt

    def make(name):
        return lambda metrics: metrics[name]

    assert _normalize_opt(make("val/loss")) != _normalize_opt(make("val/acc"))
    assert _normalize_opt(make("val/loss")) == _normalize_opt(make("val/loss"))


def test_normalize_opt_handles_arrays_and_recursion():
    """Closure cells holding arrays or self-references must normalize to
    plain comparable values — no ambiguous-truth ValueError, no infinite
    recursion."""
    import numpy as np

    from dmlcloud_tpu.checkpoint import _normalize_opt

    baseline = np.arange(4.0)

    def make():
        return lambda m: m["loss"] - baseline

    a, b = _normalize_opt(make()), _normalize_opt(make())
    assert (a == b) in (True, False)  # plain comparable, not array-ambiguous
    assert a == b

    def rec():
        def inner(x):
            return inner(x)

        return inner

    assert _normalize_opt(rec()) == _normalize_opt(rec())


class TestSlurmRequeueDiscovery:
    """The requeue half of the elastic contract (doc/elasticity.md): a
    Slurm job that is preempted and requeued comes back with the SAME job
    id in a NEW process — ``find_slurm_checkpoint`` + the indicator/
    ``.slurm-jobid`` contract files are how attempt 2 finds attempt 1's
    checkpoint dir without any state surviving in memory."""

    def _attempt1(self, root, job_id, monkeypatch, name="run-a"):
        monkeypatch.setenv("SLURM_JOB_ID", job_id)
        ckpt = CheckpointDir(root / name)
        ckpt.create()
        return ckpt

    def test_requeue_same_job_id_new_attempt(self, tmp_path, monkeypatch):
        ckpt = self._attempt1(tmp_path, "777", monkeypatch)
        # attempt 2: a fresh process (nothing but env + filesystem survive)
        monkeypatch.setenv("SLURM_JOB_ID", "777")
        found = find_slurm_checkpoint(tmp_path)
        assert found == ckpt.path
        rediscovered = CheckpointDir(found)
        assert rediscovered.is_valid
        assert rediscovered.slurm_job_id == "777"

    def test_stale_dir_without_indicator_is_skipped(self, tmp_path, monkeypatch):
        """A half-created or torn-down dir (``.slurm-jobid`` present but the
        indicator missing) must not be rediscovered — resuming from it would
        trust an unvalidated layout."""
        ckpt = self._attempt1(tmp_path, "777", monkeypatch)
        ckpt.indicator_file.unlink()
        assert not ckpt.is_valid
        assert find_slurm_checkpoint(tmp_path) is None

    def test_plain_file_and_foreign_dirs_are_skipped(self, tmp_path, monkeypatch):
        (tmp_path / "notes.txt").write_text("not a run dir")
        (tmp_path / "unrelated").mkdir()  # no indicator, no slurm file
        other = self._attempt1(tmp_path, "111", monkeypatch, name="other-job")
        assert other.slurm_job_id == "111"
        mine = self._attempt1(tmp_path, "777", monkeypatch, name="mine")
        monkeypatch.setenv("SLURM_JOB_ID", "777")
        assert find_slurm_checkpoint(tmp_path) == mine.path

    def test_missing_root_or_no_slurm_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SLURM_JOB_ID", "777")
        assert find_slurm_checkpoint(tmp_path / "never-created") is None
        monkeypatch.delenv("SLURM_JOB_ID")
        self._attempt1(tmp_path, "777", monkeypatch, name="later")
        monkeypatch.delenv("SLURM_JOB_ID")
        assert find_slurm_checkpoint(tmp_path) is None  # outside Slurm: never guess

    def test_pipeline_resume_rediscovers_by_job_id(self, tmp_path, monkeypatch, single_runtime):
        """enable_checkpointing(root, resume=True) on a requeued attempt must
        land on attempt 1's dir (resumed=True), not generate a fresh path."""
        import dmlcloud_tpu as dml

        ckpt = self._attempt1(tmp_path, "4242", monkeypatch)
        monkeypatch.setenv("SLURM_JOB_ID", "4242")
        pipe = dml.TrainingPipeline(name="requeue")
        pipe.enable_checkpointing(str(tmp_path), resume=True)
        assert pipe.resumed is True
        assert pipe.checkpoint_dir.path == ckpt.path

    def test_pipeline_resume_fresh_when_job_id_unknown(self, tmp_path, monkeypatch, single_runtime):
        import dmlcloud_tpu as dml

        self._attempt1(tmp_path, "4242", monkeypatch)
        monkeypatch.setenv("SLURM_JOB_ID", "5555")  # a different job entirely
        pipe = dml.TrainingPipeline(name="requeue")
        pipe.enable_checkpointing(str(tmp_path), resume=True)
        assert pipe.resumed is False
        assert pipe.checkpoint_dir.path.parent == tmp_path
