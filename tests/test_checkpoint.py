"""Checkpoint dir contract, Slurm rediscovery, config round-trip, and Orbax
tensor-state save/restore (the capability the reference leaves to user hooks)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.checkpoint import (
    CheckpointDir,
    find_slurm_checkpoint,
    generate_checkpoint_path,
    generate_id,
)


def test_generate_id_urlsafe():
    i = generate_id(12)
    assert len(i) == 12
    assert i.isalnum()


def test_generate_checkpoint_path(tmp_path):
    p = generate_checkpoint_path(tmp_path, "exp/1")
    assert p.parent == tmp_path
    assert p.name.startswith("exp_1-")  # slash sanitized
    assert p != generate_checkpoint_path(tmp_path, "exp/1")


def test_create_and_validity(tmp_path):
    ckpt = CheckpointDir(tmp_path / "run")
    assert not ckpt.is_valid
    ckpt.create()
    assert ckpt.is_valid
    assert ckpt.log_file.exists()
    with pytest.raises(RuntimeError):
        ckpt.create()


def test_config_roundtrip(tmp_path):
    ckpt = CheckpointDir(tmp_path / "run")
    ckpt.create()
    ckpt.save_config({"lr": 0.1, "model": {"depth": 3}})
    cfg = ckpt.load_config()
    assert cfg.lr == 0.1
    assert cfg.model.depth == 3


def test_slurm_rediscovery(tmp_path, monkeypatch):
    monkeypatch.setenv("SLURM_JOB_ID", "4242")
    ckpt = CheckpointDir(tmp_path / "run-a")
    ckpt.create()
    assert ckpt.slurm_job_id == "4242"

    found = find_slurm_checkpoint(tmp_path)
    assert found == ckpt.path

    monkeypatch.setenv("SLURM_JOB_ID", "9999")
    assert find_slurm_checkpoint(tmp_path) is None


def test_orbax_state_roundtrip(tmp_path, single_runtime):
    ckpt = CheckpointDir(tmp_path / "run")
    ckpt.create()
    state = {"w": jnp.arange(8.0), "step": jnp.int32(5)}
    ckpt.save_state(0, state)
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 0

    restored = ckpt.restore_state(template=state)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(8.0))
    assert int(restored["step"]) == 5
    ckpt.close()
