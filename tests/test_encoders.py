"""Encoder model families: ViT, BERT, CLIP — shapes, losses, mask semantics,
sharded training, and the global-batch contrastive gather."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlcloud_tpu.models.bert import (
    IGNORE_INDEX,
    BertConfig,
    BertForMaskedLM,
    BertForSequenceClassification,
    mlm_loss,
)
from dmlcloud_tpu.models.clip import CLIP, CLIPConfig, CLIPTextConfig, clip_loss
from dmlcloud_tpu.models.encoder import encoder_partition_rules
from dmlcloud_tpu.models.vit import ViT, ViTConfig
from dmlcloud_tpu.parallel import mesh as mesh_lib
from dmlcloud_tpu.train_state import TrainState

VIT_TINY = ViTConfig(
    image_size=32, patch_size=8, hidden_dim=64, num_layers=2, num_heads=4,
    mlp_dim=128, num_classes=10, dtype=jnp.float32,
)
BERT_TINY = BertConfig(
    vocab_size=128, max_seq_len=32, hidden_dim=64, num_layers=2, num_heads=4,
    mlp_dim=128, dtype=jnp.float32,
)


def test_vit_forward_shapes():
    model = ViT(VIT_TINY)
    images = jnp.zeros((2, 32, 32, 3))
    params = model.init(jax.random.PRNGKey(0), images)
    out = model.apply(params, images)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_vit_gap_and_features():
    import dataclasses

    cfg = dataclasses.replace(VIT_TINY, pooling="gap", num_classes=0)
    model = ViT(cfg)
    images = jnp.ones((2, 32, 32, 3))
    params = model.init(jax.random.PRNGKey(0), images)
    feats = model.apply(params, images)
    assert feats.shape == (2, 64)


def test_vit_b16_param_count():
    from dmlcloud_tpu.models.vit import ViT_B16

    model = ViT_B16(num_classes=1000)
    vars_ = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
    )
    n = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(vars_["params"]))
    assert 85e6 < n < 88e6  # ViT-B/16 is ~86.6M params


def test_bert_mlm_loss_at_init():
    model = BertForMaskedLM(BERT_TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, BERT_TINY.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, BERT_TINY.vocab_size)
    labels = tokens.at[:, ::2].set(IGNORE_INDEX)  # mask out half the positions
    loss = mlm_loss(logits, labels)
    assert float(loss) == pytest.approx(np.log(BERT_TINY.vocab_size), rel=0.2)


def test_mlm_loss_ignores_masked_positions():
    logits = jnp.zeros((1, 4, 8)).at[0, 0, 3].set(100.0)
    labels_all_ignored = jnp.full((1, 4), IGNORE_INDEX)
    assert float(mlm_loss(logits, labels_all_ignored)) == 0.0
    labels = labels_all_ignored.at[0, 0].set(3)
    assert float(mlm_loss(logits, labels)) == pytest.approx(0.0, abs=1e-5)


def test_bert_attention_mask_blocks_padding():
    """Masked-out padding tokens must not influence other positions."""
    model = BertForMaskedLM(BERT_TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (1, 16), 0, BERT_TINY.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens)
    mask = jnp.ones((1, 16)).at[0, 8:].set(0)

    logits_a = model.apply(params, tokens, attention_mask=mask)
    garbage = tokens.at[0, 8:].set((tokens[0, 8:] + 7) % BERT_TINY.vocab_size)
    logits_b = model.apply(params, garbage, attention_mask=mask)
    np.testing.assert_allclose(
        np.asarray(logits_a[0, :8]), np.asarray(logits_b[0, :8]), atol=1e-5
    )


def test_bert_classifier_shapes():
    model = BertForSequenceClassification(BERT_TINY, num_classes=3)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    out = model.apply(params, tokens)
    assert out.shape == (2, 3)


@pytest.mark.slow
def test_bert_sharded_finetune_step():
    """BERT fine-tune (the BASELINE ladder rung) on a data+model mesh."""
    mesh = mesh_lib.create_mesh({"data": 4, "model": 2})
    model = BertForSequenceClassification(BERT_TINY, num_classes=2)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, BERT_TINY.vocab_size)
    labels = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 2)
    params = model.init(jax.random.PRNGKey(2), tokens[:1])

    state = TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=optax.adam(1e-3),
        mesh=mesh,
        policy=encoder_partition_rules(),
    )
    batch = mesh_lib.make_global_batch(tokens, mesh)
    y = mesh_lib.make_global_batch(labels, mesh)

    @jax.jit
    def step(state, batch, y):
        def loss_fn(p):
            logits = state.apply_fn(p, batch)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads), loss

    losses = []
    for _ in range(5):
        state, loss = step(state, batch, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_bert_fsdp_mesh_placement():
    """Regression: rules matching indivisible dims (the 2-row type-embedding
    table vs P('fsdp', ...)) must relocate the axis to a divisible dim — or
    replicate — instead of crashing placement."""
    mesh = mesh_lib.create_mesh({"data": 2, "fsdp": 4})
    model = BertForMaskedLM(BERT_TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, BERT_TINY.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens[:1])

    state = TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=optax.adam(1e-3),
        mesh=mesh,
        policy=encoder_partition_rules(),
    )
    # the word-embedding table (128 rows) is sharded over fsdp on dim 0...
    embeddings = state.params["params"]["bert"]["embeddings"]
    word_spec = embeddings["word"]["embedding"].sharding.spec
    assert word_spec[0] == "fsdp"
    # ...while the 2-row type table had its fsdp shards relocated to the
    # (divisible) hidden dim instead of crashing or silently replicating
    type_spec = embeddings["type"]["embedding"].sharding.spec
    assert tuple(type_spec) == (None, "fsdp")

    batch = mesh_lib.make_global_batch(tokens, mesh)
    logits = jax.jit(state.apply_fn)(state.params, batch)
    assert logits.shape == (8, 16, BERT_TINY.vocab_size)


CLIP_TINY = CLIPConfig(
    embed_dim=32,
    vision=ViTConfig(
        image_size=16, patch_size=8, hidden_dim=32, num_layers=1, num_heads=2,
        mlp_dim=64, num_classes=0, dtype=jnp.float32,
    ),
    text=CLIPTextConfig(
        vocab_size=64, max_seq_len=12, hidden_dim=32, num_layers=1, num_heads=2,
        mlp_dim=64, dtype=jnp.float32,
    ),
)


def _clip_batch(n):
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.rand(n, 16, 16, 3), jnp.float32)
    tokens = jnp.asarray(rng.randint(1, 63, (n, 12)), jnp.int32)
    tokens = tokens.at[:, -1].set(63)  # EOT = highest id
    return images, tokens


@pytest.mark.slow
def test_clip_forward_and_loss():
    model = CLIP(CLIP_TINY)
    images, tokens = _clip_batch(4)
    params = model.init(jax.random.PRNGKey(0), images, tokens)
    img, txt, scale = model.apply(params, images, tokens)
    assert img.shape == (4, 32) and txt.shape == (4, 32)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(img), axis=-1), 1.0, atol=1e-5)
    loss = clip_loss(img, txt, scale)
    assert np.isfinite(float(loss))
    # at init the large logit scale (1/0.07) spreads random similarities, so
    # just bound it near the uniform value rather than pin it
    assert 0.0 < float(loss) < 4.0 * np.log(4)


def test_clip_global_batch_loss_matches_single_device():
    """shard_mapped clip_loss with all_gather over 'data' == unsharded loss."""
    from jax.experimental.shard_map import shard_map

    mesh = mesh_lib.create_mesh({"data": 8})
    rng = np.random.RandomState(1)
    img = jnp.asarray(rng.randn(16, 8), jnp.float32)
    txt = jnp.asarray(rng.randn(16, 8), jnp.float32)
    img = img / jnp.linalg.norm(img, axis=-1, keepdims=True)
    txt = txt / jnp.linalg.norm(txt, axis=-1, keepdims=True)
    scale = jnp.float32(10.0)

    expected = float(clip_loss(img, txt, scale))

    sharded = shard_map(
        lambda i, t: jax.lax.pmean(clip_loss(i, t, scale, axis_name="data"), "data")[None],
        mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=P(None),
    )
    got = float(sharded(img, txt)[0])
    assert got == pytest.approx(expected, rel=1e-5)


@pytest.mark.slow
def test_encoder_flash_attention_matches_dot():
    """attn_impl='flash' (unmasked path) must match the einsum softmax, in
    both directions, causal and not."""
    import jax
    import numpy as np

    from dmlcloud_tpu.models.encoder import EncoderConfig, TransformerEncoder

    for causal in (False, True):
        cfg = EncoderConfig(hidden_dim=32, num_layers=2, num_heads=2, mlp_dim=64,
                            dtype=jnp.float32, causal=causal)
        cfg_flash = EncoderConfig(**{**cfg.__dict__, "attn_impl": "flash"})
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 32))
        params = TransformerEncoder(cfg).init(jax.random.PRNGKey(1), x)

        out_dot = TransformerEncoder(cfg).apply(params, x)
        out_flash = TransformerEncoder(cfg_flash).apply(params, x)
        np.testing.assert_allclose(np.asarray(out_dot), np.asarray(out_flash), atol=2e-4, rtol=2e-4)

        g_dot = jax.grad(lambda p: jnp.sum(TransformerEncoder(cfg).apply(p, x) ** 2))(params)
        g_flash = jax.grad(lambda p: jnp.sum(TransformerEncoder(cfg_flash).apply(p, x) ** 2))(params)
        for a, b in zip(jax.tree_util.tree_leaves(g_dot), jax.tree_util.tree_leaves(g_flash)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)


def test_encoder_flash_with_padding_mask_falls_back():
    """A padding mask routes through the bias path even under attn_impl='flash'
    — same numbers as 'dot' with the same mask."""
    import jax
    import numpy as np

    from dmlcloud_tpu.models.encoder import EncoderConfig, TransformerEncoder, padding_mask_bias

    cfg = EncoderConfig(hidden_dim=32, num_layers=1, num_heads=2, mlp_dim=64, dtype=jnp.float32)
    cfg_flash = EncoderConfig(**{**cfg.__dict__, "attn_impl": "flash"})
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 32))
    mask = jnp.asarray(np.repeat([[1] * 48 + [0] * 16], 2, axis=0))
    bias = padding_mask_bias(mask)
    params = TransformerEncoder(cfg).init(jax.random.PRNGKey(1), x)
    out_dot = TransformerEncoder(cfg).apply(params, x, bias)
    out_flash = TransformerEncoder(cfg_flash).apply(params, x, bias)
    np.testing.assert_allclose(np.asarray(out_dot), np.asarray(out_flash), atol=1e-5, rtol=1e-5)


def test_invalid_attn_impl_rejected():
    import pytest

    from dmlcloud_tpu.models.encoder import EncoderConfig
    from dmlcloud_tpu.models.transformer import TransformerConfig

    with pytest.raises(ValueError, match="attn_impl"):
        EncoderConfig(attn_impl="Flash")
    with pytest.raises(ValueError, match="attn_impl"):
        TransformerConfig(attn_impl="pallas")


def test_bert_padded_flash_matches_dot_on_real_positions():
    """A padded batch on the flash path (keep-mask as kernel segment ids)
    must match the dot/bias path at every REAL position (pad outputs differ
    by design and are masked downstream)."""
    from dmlcloud_tpu.models.bert import BertConfig, BertEncoder

    kw = dict(vocab_size=61, hidden_dim=32, num_heads=2, mlp_dim=64,
              num_layers=2, max_seq_len=64, dtype=jnp.float32)
    cfg_dot = BertConfig(**kw, attn_impl="dot")
    cfg_flash = BertConfig(**kw, attn_impl="flash")
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 61, size=(2, 64)).astype(np.int32)
    mask = np.ones((2, 64), np.int32)
    mask[0, 50:] = 0
    mask[1, 33:] = 0

    model_dot, model_flash = BertEncoder(cfg_dot), BertEncoder(cfg_flash)
    params = model_dot.init(jax.random.PRNGKey(0), jnp.asarray(tokens))["params"]
    out_dot = model_dot.apply({"params": params}, jnp.asarray(tokens), jnp.asarray(mask))
    out_flash = model_flash.apply({"params": params}, jnp.asarray(tokens), jnp.asarray(mask))
    for r in range(2):
        real = mask[r].astype(bool)
        np.testing.assert_allclose(
            np.asarray(out_dot)[r][real], np.asarray(out_flash)[r][real], atol=2e-4, rtol=2e-4
        )
