"""Step-granular checkpointing: a preemption mid-epoch exits at the next
step boundary with the state saved, the resumed run fast-forwards the data
to the exact batch, and the final params are bit-identical to a run that
was never interrupted (deterministic per-epoch data + rng folded by global
step make the two trajectories the same computation)."""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax

import dmlcloud_tpu as dml

BATCHES_PER_EPOCH = 10
SAVE_EVERY = 3


def _make_batches():
    rng = np.random.RandomState(0)
    xs = rng.randn(BATCHES_PER_EPOCH, 16, 4).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    return [{"x": x, "y": x @ w} for x in xs]


class _PreemptAfter:
    """List-like dataset that raises SIGUSR1 after yielding batch K — the
    real preemption path (signal -> coordinated poll at the save point)."""

    def __init__(self, batches, kill_after=None):
        self._batches = batches
        self._kill_after = kill_after
        self.fired = False

    def __iter__(self):
        for i, b in enumerate(self._batches):
            yield b
            if self._kill_after is not None and not self.fired and i + 1 == self._kill_after:
                self.fired = True
                os.kill(os.getpid(), signal.SIGUSR1)

    def __len__(self):
        return len(self._batches)


class _Stage(dml.TrainValStage):
    def __init__(self, dataset, every_steps=SAVE_EVERY):
        super().__init__()
        self._dataset = dataset
        self._every = every_steps

    def checkpoint_every_steps(self):
        return self._every

    def device_prefetch(self):
        return 0  # keep batch consumption aligned with steps for the test

    def pre_stage(self):
        import flax.linen as nn

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(1, use_bias=False)(x)

        model = Lin()
        self.pipeline.register_model(
            "lin", model, params=model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4))),
            verbose=False,
        )
        self.pipeline.register_optimizer("sgd", optax.sgd(0.05))
        self.pipeline.register_dataset("train", self._dataset, verbose=False)

    def step(self, state, batch):
        pred = state.apply_fn({"params": state.params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    def val_epoch(self):
        pass


def _run(tmp_path, dataset, epochs=2, every_steps=SAVE_EVERY, preemptible=False):
    pipe = dml.TrainingPipeline(name="stepckpt")
    pipe.enable_checkpointing(str(tmp_path), resume=True)
    if preemptible:
        pipe.enable_preemption_handling(("SIGUSR1",))
    stage = _Stage(dataset, every_steps)
    pipe.append_stage(stage, max_epochs=epochs)
    pipe.run()
    return pipe, stage


def test_preempt_mid_epoch_then_resume_bit_identical(tmp_path):
    batches = _make_batches()

    # control: never interrupted
    _, control = _run(tmp_path / "control", batches)
    want = np.asarray(control.state.params["Dense_0"]["kernel"])
    assert int(control.state.step) == 2 * BATCHES_PER_EPOCH

    # interrupted: SIGUSR1 after batch 5 of epoch 1 -> the step-boundary
    # poll at step 6 saves and exits mid-epoch
    ds = _PreemptAfter(batches, kill_after=5)
    pipe1, stage1 = _run(tmp_path / "run", ds, preemptible=True)
    assert stage1._mid_epoch_exit and stage1._preempt_exit
    assert int(stage1.state.step) == 6
    # epoch 1 is NOT recorded as complete
    assert pipe1.checkpoint_dir.latest_step(scope=stage1.name) is None
    assert pipe1.checkpoint_dir.latest_step(scope=f"{stage1.name}.steps") == 6

    # resume: finishes epoch 1 from batch 7 and runs epoch 2
    pipe2, stage2 = _run(pipe1.checkpoint_dir.path, _PreemptAfter(batches))
    assert int(stage2.state.step) == 2 * BATCHES_PER_EPOCH
    got = np.asarray(stage2.state.params["Dense_0"]["kernel"])
    np.testing.assert_array_equal(got, want)
    # the resumed epoch's metrics covered the remaining 4 steps only
    # (documented caveat), but both epochs are recorded
    assert len(stage2.tracker["train/loss"]) == 2


def test_completed_epoch_supersedes_older_step_save(tmp_path):
    batches = _make_batches()
    pipe, stage = _run(tmp_path, batches, epochs=1)
    # the run completed epoch 1 (and left a step save from inside it)
    assert pipe.checkpoint_dir.latest_step(scope=stage.name) == 1
    assert pipe.checkpoint_dir.latest_step(scope=f"{stage.name}.steps") is not None

    pipe2, stage2 = _run(pipe.checkpoint_dir.path, batches, epochs=1)
    # nothing retrains: the epoch save wins over the stale mid-epoch save
    assert stage2.current_epoch == 2
    assert int(stage2.state.step) == BATCHES_PER_EPOCH


def test_tracker_fast_forward_pads_gap_epochs():
    from dmlcloud_tpu.metrics import MetricTracker, Reduction

    tr = MetricTracker()
    tr.register_metric("m", Reduction.MEAN)
    tr.track("m", 1.0)
    tr.next_epoch()  # epoch 1 recorded, now at 2
    tr.fast_forward(5)
    assert tr.epoch == 5
    tr.track("m", 9.0)
    tr.next_epoch()
    # epoch-5 value lands at index 4; gap epochs are None
    assert list(tr["m"]) == [1.0, None, None, None, 9.0]
    tr.fast_forward(3)  # no-op backwards
    assert tr.epoch == 6


class _ManualEpochStage(_Stage):
    def checkpoint_every(self):
        return 0  # manual epoch checkpointing: step saves must still resume


def test_step_only_mode_still_resumes(tmp_path):
    batches = _make_batches()
    ds = _PreemptAfter(batches, kill_after=5)
    pipe = dml.TrainingPipeline(name="steponly")
    pipe.enable_checkpointing(str(tmp_path), resume=True)
    pipe.enable_preemption_handling(("SIGUSR1",))
    stage = _ManualEpochStage(ds)
    pipe.append_stage(stage, max_epochs=2)
    pipe.run()
    assert int(stage.state.step) == 6

    pipe2 = dml.TrainingPipeline(name="steponly")
    pipe2.enable_checkpointing(str(pipe.checkpoint_dir.path), resume=True)
    stage2 = _ManualEpochStage(_PreemptAfter(batches))
    pipe2.append_stage(stage2, max_epochs=2)
    pipe2.run()
    # resumed mid-epoch from the step save despite checkpoint_every()==0
    assert int(stage2.state.step) == 2 * BATCHES_PER_EPOCH


def test_corrupt_sidecar_step_only_mode_still_restores_weights(tmp_path):
    """Step-only mode with an unusable sidecar must restore the weights
    (epoch position lost, loop restarts) — not silently train from scratch."""
    batches = _make_batches()
    pipe = dml.TrainingPipeline(name="blind")
    pipe.enable_checkpointing(str(tmp_path), resume=True)
    pipe.enable_preemption_handling(("SIGUSR1",))
    stage = _ManualEpochStage(_PreemptAfter(batches, kill_after=5))
    pipe.append_stage(stage, max_epochs=2)
    pipe.run()
    assert int(stage.state.step) == 6

    meta = pipe.checkpoint_dir.path / "meta" / f"{stage.name}.steps" / "6.json"
    meta.write_text("{corrupt")

    pipe2 = dml.TrainingPipeline(name="blind")
    pipe2.enable_checkpointing(str(pipe.checkpoint_dir.path), resume=True)
    stage2 = _ManualEpochStage(_PreemptAfter(batches))
    pipe2.append_stage(stage2, max_epochs=2)
    pipe2.run()
    # restored global step 6, then re-ran BOTH epochs from their start
    assert int(stage2.state.step) == 6 + 2 * BATCHES_PER_EPOCH


def test_interrupted_inflight_step_save_resumes_from_committed(tmp_path):
    """A kill with a mid-epoch step save still in flight (async writer never
    committed) must resume from the last COMMITTED step save — the planted
    Orbax tmp dir emulates exactly what the kill leaves on disk."""
    batches = _make_batches()
    ds = _PreemptAfter(batches, kill_after=5)
    pipe1, stage1 = _run(tmp_path / "run", ds, preemptible=True)
    assert int(stage1.state.step) == 6

    # the kill artifact: a step-9 save that never committed
    steps_dir = pipe1.checkpoint_dir.state_dir / f"{stage1.name}.steps"
    (steps_dir / "9.orbax-checkpoint-tmp-1234567890").mkdir()
    assert pipe1.checkpoint_dir.latest_step(scope=f"{stage1.name}.steps") == 6

    _, control = _run(tmp_path / "control", batches)
    pipe2, stage2 = _run(pipe1.checkpoint_dir.path, _PreemptAfter(batches))
    assert int(stage2.state.step) == 2 * BATCHES_PER_EPOCH
    np.testing.assert_array_equal(
        np.asarray(stage2.state.params["Dense_0"]["kernel"]),
        np.asarray(control.state.params["Dense_0"]["kernel"]),
    )


def test_step_saves_sync_mode_bit_identical(tmp_path):
    """async_checkpoint() False through the mid-epoch preempt/resume path
    must land on the same weights as the async default."""

    class SyncStage(_Stage):
        def async_checkpoint(self):
            return False

    batches = _make_batches()
    _, control = _run(tmp_path / "control", batches)

    ds = _PreemptAfter(batches, kill_after=5)
    pipe1 = dml.TrainingPipeline(name="syncstep")
    pipe1.enable_checkpointing(str(tmp_path / "sync"), resume=True)
    pipe1.enable_preemption_handling(("SIGUSR1",))
    stage1 = SyncStage(ds)
    pipe1.append_stage(stage1, max_epochs=2)
    pipe1.run()
    assert int(stage1.state.step) == 6

    pipe2 = dml.TrainingPipeline(name="syncstep")
    pipe2.enable_checkpointing(str(pipe1.checkpoint_dir.path), resume=True)
    stage2 = SyncStage(_PreemptAfter(batches))
    pipe2.append_stage(stage2, max_epochs=2)
    pipe2.run()
    np.testing.assert_array_equal(
        np.asarray(stage2.state.params["Dense_0"]["kernel"]),
        np.asarray(control.state.params["Dense_0"]["kernel"]),
    )


def test_step_saves_disabled_by_default(tmp_path):
    batches = _make_batches()
    pipe, stage = _run(tmp_path, batches, epochs=1, every_steps=0)
    assert pipe.checkpoint_dir.latest_step(scope=f"{stage.name}.steps") is None
