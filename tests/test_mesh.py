"""Mesh construction, sharding policies, and real multi-device psum on the
8-device CPU mesh — stronger than the reference's world-1 trick (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dmlcloud_tpu.parallel import mesh as mesh_lib


def test_create_default_mesh():
    m = mesh_lib.create_mesh()
    assert m.axis_names == ("data",)
    assert m.shape["data"] == 8


def test_create_mesh_with_minus_one():
    m = mesh_lib.create_mesh({"data": -1, "model": 2})
    assert m.shape["data"] == 4
    assert m.shape["model"] == 2


def test_create_mesh_wrong_product():
    with pytest.raises(ValueError):
        mesh_lib.create_mesh({"data": 3})


def test_auto_mesh_factorization():
    m = mesh_lib.auto_mesh(8, ("data", "fsdp", "model"))
    sizes = [m.shape[a] for a in ("data", "fsdp", "model")]
    assert np.prod(sizes) == 8
    assert sizes == [2, 2, 2]


def test_batch_pspec_with_fsdp():
    m = mesh_lib.create_mesh({"data": 2, "fsdp": 4})
    assert mesh_lib.batch_pspec(m) == P(("data", "fsdp"))
    assert mesh_lib.data_parallel_size(m) == 8


def test_replicate_policy(mesh8):
    params = {"w": jnp.ones((16, 4)), "b": jnp.zeros((4,))}
    sharded = mesh_lib.shard_pytree(params, mesh8, "replicate")
    for leaf in jax.tree_util.tree_leaves(sharded):
        assert leaf.sharding.is_fully_replicated


def test_fsdp_policy_shards_large_params():
    m = mesh_lib.create_mesh({"fsdp": 8})
    params = {"big": jnp.ones((1024, 64)), "tiny": jnp.ones((4,))}
    shardings = mesh_lib.sharding_for(params, m, "fsdp")
    assert shardings["big"].spec == P("fsdp", None)
    assert shardings["tiny"].spec == P()


def test_rule_policy():
    m = mesh_lib.create_mesh({"data": 4, "model": 2})
    params = {"attn": {"kernel": jnp.ones((8, 16))}, "mlp": {"kernel": jnp.ones((8, 16))}}
    rules = [("attn/kernel", P(None, "model")), (".*", P())]
    shardings = mesh_lib.sharding_for(params, m, rules)
    assert shardings["attn"]["kernel"].spec == P(None, "model")
    assert shardings["mlp"]["kernel"].spec == P()


def test_rule_policy_drops_missing_axes():
    m = mesh_lib.create_mesh({"data": -1})  # no 'model' axis
    params = {"attn": {"kernel": jnp.ones((8, 16))}}
    rules = [("attn/kernel", P(None, "model"))]
    shardings = mesh_lib.sharding_for(params, m, rules)
    assert shardings["attn"]["kernel"].spec == P(None, None)


def test_make_global_batch_shards_batch_dim(mesh8):
    batch = {"x": np.arange(32, dtype=np.float32).reshape(16, 2), "y": np.arange(16)}
    global_batch = mesh_lib.make_global_batch(batch, mesh8)
    assert global_batch["x"].shape == (16, 2)
    # 8 shards of 2 rows each
    assert len(global_batch["x"].sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(global_batch["x"]), batch["x"])


def test_sharded_psum_executes(mesh8):
    """A real 8-way psum through shard_map — the collective path DDP used to own."""
    from dmlcloud_tpu.parallel.mesh import shard_map_compat

    x = jnp.arange(8.0)

    def global_sum(x):
        return jax.lax.psum(jnp.sum(x), "data")

    global_sum = shard_map_compat(global_sum, mesh=mesh8, in_specs=P("data"), out_specs=P())
    assert float(global_sum(x)) == 28.0


def test_grad_mean_matches_single_device(mesh8):
    """Data-parallel grad via sharded jit == single-device grad on full batch."""
    w = jnp.ones((4,))
    x = np.random.RandomState(0).randn(16, 4).astype(np.float32)

    def loss(w, x):
        return jnp.mean((x @ w) ** 2)

    expected = jax.grad(loss)(w, jnp.asarray(x))

    xs = mesh_lib.make_global_batch(x, mesh8)
    sharded_grad = jax.jit(jax.grad(loss))(w, xs)
    np.testing.assert_allclose(np.asarray(sharded_grad), np.asarray(expected), rtol=1e-5)


def test_parse_mesh_axes():
    from dmlcloud_tpu.parallel.mesh import parse_mesh_axes

    assert parse_mesh_axes("data=2,fsdp=4") == {"data": 2, "fsdp": 4}
    assert parse_mesh_axes("data=-1") == {"data": -1}


def test_parse_mesh_axes_rejects_malformed():
    import pytest

    from dmlcloud_tpu.parallel.mesh import parse_mesh_axes

    with pytest.raises(ValueError, match="malformed"):
        parse_mesh_axes("data")
    with pytest.raises(ValueError, match="malformed"):
        parse_mesh_axes("data=two")


def test_parse_mesh_axes_rejects_duplicate_axis():
    """'data=2,data=4' used to silently become {'data': 4} — a dict overwrite
    that dropped the first size without a word."""
    import pytest

    from dmlcloud_tpu.parallel.mesh import parse_mesh_axes

    with pytest.raises(ValueError, match="more than once"):
        parse_mesh_axes("data=2,data=4")
