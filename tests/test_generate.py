"""KV-cache generation (models/generate.py): the cached decode loop must
reproduce the no-cache model exactly (greedy), honor eos/pad semantics, and
run the MoE variant. fp32 config so CPU comparisons are exact-ish."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.models.generate import generate, init_cache
from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig


def _tiny_cfg(**kw):
    base = dict(
        vocab_size=61,
        num_layers=2,
        num_heads=4,
        head_dim=8,
        hidden_dim=32,
        mlp_dim=64,
        max_seq_len=64,
        dtype=jnp.float32,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _init(cfg, batch=2, t=7, seed=0):
    model = DecoderLM(cfg)
    rng = np.random.RandomState(seed)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(batch, t)), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), prompt)["params"]
    return model, params, prompt


def _greedy_no_cache(model, params, prompt, n):
    """Reference: rerun the full model per token, argmax the last position."""
    tokens = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, tokens)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


@pytest.mark.slow
def test_greedy_matches_no_cache():
    cfg = _tiny_cfg()
    model, params, prompt = _init(cfg)
    want = _greedy_no_cache(model, params, prompt, 8)
    got = generate(model, params, prompt, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_gqa_greedy_matches_no_cache():
    cfg = _tiny_cfg(num_kv_heads=2)
    model, params, prompt = _init(cfg)
    want = _greedy_no_cache(model, params, prompt, 6)
    got = generate(model, params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_eos_rows_emit_pad():
    cfg = _tiny_cfg()
    model, params, prompt = _init(cfg)
    first = np.asarray(generate(model, params, prompt, max_new_tokens=1))[:, 0]
    out = np.asarray(
        generate(model, params, prompt, max_new_tokens=6, eos_id=int(first[0]), pad_id=59)
    )
    # row 0 hit eos at step 0: the eos token itself is emitted, then pad
    assert out[0, 0] == first[0]
    assert (out[0, 1:] == 59).all()


def test_sampling_deterministic_under_rng():
    cfg = _tiny_cfg()
    model, params, prompt = _init(cfg)
    a = generate(model, params, prompt, 5, temperature=0.8, top_k=10, rng=jax.random.PRNGKey(7))
    b = generate(model, params, prompt, 5, temperature=0.8, top_k=10, rng=jax.random.PRNGKey(7))
    c = generate(model, params, prompt, 5, temperature=0.8, top_k=10, rng=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(a).shape == (2, 5)
    assert ((np.asarray(a) >= 0) & (np.asarray(a) < cfg.vocab_size)).all()
    # different seed should (overwhelmingly) differ somewhere
    assert not np.array_equal(np.asarray(a), np.asarray(c))


@pytest.mark.slow
def test_moe_decode_runs():
    cfg = _tiny_cfg(num_experts=2, moe_every=2)
    model, params, prompt = _init(cfg)
    out = generate(model, params, prompt, max_new_tokens=4)
    assert np.asarray(out).shape == (2, 4)


def test_length_guard():
    cfg = _tiny_cfg(max_seq_len=16)
    model, params, prompt = _init(cfg, t=12)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, max_new_tokens=8)


def test_init_cache_shapes():
    cfg = _tiny_cfg(num_kv_heads=2)
    cache = init_cache(cfg, batch_size=3, max_len=32)
    assert set(cache) == {"layer_0", "layer_1"}
    assert cache["layer_0"]["k"].shape == (3, 32, 2, 8)


@pytest.mark.slow
def test_top_p_sampling():
    cfg = _tiny_cfg()
    model, params, prompt = _init(cfg)
    # tiny nucleus -> effectively greedy (only the argmax survives the cutoff)
    tight = generate(model, params, prompt, 5, temperature=1.0, top_p=1e-6, rng=jax.random.PRNGKey(3))
    greedy = generate(model, params, prompt, 5)
    np.testing.assert_array_equal(np.asarray(tight), np.asarray(greedy))
    # permissive nucleus is deterministic under a fixed rng and in range
    a = generate(model, params, prompt, 5, temperature=1.0, top_p=0.9, rng=jax.random.PRNGKey(4))
    b = generate(model, params, prompt, 5, temperature=1.0, top_p=0.9, rng=jax.random.PRNGKey(4))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ((np.asarray(a) >= 0) & (np.asarray(a) < cfg.vocab_size)).all()


class TestBeamSearch:
    def test_single_beam_equals_greedy(self):
        from dmlcloud_tpu.models.generate import beam_search

        cfg = _tiny_cfg()
        model, params, prompt = _init(cfg)
        greedy = generate(model, params, prompt, 7)
        beams, scores = beam_search(model, params, prompt, 7, num_beams=1)
        np.testing.assert_array_equal(np.asarray(beams), np.asarray(greedy))
        assert np.isfinite(np.asarray(scores)).all()

    @pytest.mark.slow
    def test_full_beam_finds_global_optimum(self):
        """With K = V^(N-1) beams, beam search is exhaustive: its winner must
        be the true argmax over all V^N continuations, scored by rerunning
        the full model."""
        from itertools import product

        from dmlcloud_tpu.models.generate import beam_search

        cfg = _tiny_cfg(vocab_size=16, max_seq_len=16)
        model, params, prompt = _init(cfg, batch=1, t=3)
        n = 2  # K = V^(N-1) = 16 beams make the search exhaustive
        beams, score = beam_search(model, params, prompt, n, num_beams=16)

        def seq_logprob(cont):
            toks = jnp.concatenate([prompt, jnp.asarray([cont], jnp.int32)], axis=1)
            logits = model.apply({"params": params}, toks)
            lp = jax.nn.log_softmax(logits[0].astype(jnp.float32))
            return sum(float(lp[prompt.shape[1] - 1 + j, cont[j]]) for j in range(n))

        all_scores = {cont: seq_logprob(cont) for cont in product(range(16), repeat=n)}
        best_cont = max(all_scores, key=all_scores.get)
        assert tuple(np.asarray(beams)[0].tolist()) == best_cont
        assert abs(float(score[0]) - all_scores[best_cont] / n) < 1e-4  # len-normalised

    @pytest.mark.slow
    def test_beam_scores_are_honest(self):
        """The reported score must equal rescoring the winning continuation
        with the full model (beam >= greedy is NOT asserted — the greedy
        prefix can legitimately be pruned mid-search)."""
        from dmlcloud_tpu.models.generate import beam_search

        cfg = _tiny_cfg(vocab_size=13)
        model, params, prompt = _init(cfg, batch=3, t=5, seed=2)
        beams, scores = beam_search(model, params, prompt, 6, num_beams=4)
        assert np.asarray(beams).shape == (3, 6)

        def score_cont(cont_row, prompt_row):
            toks = jnp.concatenate([prompt_row[None], cont_row[None]], axis=1)
            logits = model.apply({"params": params}, toks)
            lp = jax.nn.log_softmax(logits[0].astype(jnp.float32))
            t0 = prompt_row.shape[0]
            return sum(float(lp[t0 - 1 + j, int(cont_row[j])]) for j in range(6)) / 6

        for i in range(3):
            s_beam = score_cont(jnp.asarray(np.asarray(beams)[i]), prompt[i])
            assert abs(s_beam - float(scores[i])) < 1e-4  # reported score is honest

    def test_eos_freezes_beams(self):
        from dmlcloud_tpu.models.generate import beam_search

        cfg = _tiny_cfg()
        model, params, prompt = _init(cfg)
        first = np.asarray(generate(model, params, prompt, 1))[:, 0]
        beams, _ = beam_search(
            model, params, prompt, 6, num_beams=1, eos_id=int(first[0]), pad_id=59
        )
        out = np.asarray(beams)
        assert out[0, 0] == first[0]
        assert (out[0, 1:] == 59).all()

    def test_validation(self):
        from dmlcloud_tpu.models.generate import beam_search

        cfg = _tiny_cfg()
        model, params, prompt = _init(cfg)
        with pytest.raises(ValueError, match="num_beams"):
            beam_search(model, params, prompt, 4, num_beams=0)
        with pytest.raises(ValueError, match="vocab"):
            beam_search(model, params, prompt, 4, num_beams=100)

    @pytest.mark.slow
    def test_eos_freezes_multi_beam(self):
        """With k > 1, any beam that emits eos must continue as pure pad
        (exercises reorder + freeze interaction, not just the k=1 identity)."""
        from dmlcloud_tpu.models.generate import beam_search

        cfg = _tiny_cfg()
        for seed in range(3):
            model, params, prompt = _init(cfg, batch=2, t=5, seed=seed)
            first = int(np.asarray(generate(model, params, prompt, 1))[0, 0])
            beams, scores = beam_search(
                model, params, prompt, 6, num_beams=3, eos_id=first, pad_id=59
            )
            out = np.asarray(beams)
            assert np.isfinite(np.asarray(scores)).all()
            for row in out:
                hits = np.where(row == first)[0]
                if hits.size:
                    assert (row[hits[0] + 1 :] == 59).all()

    def test_pad_id_validated(self):
        from dmlcloud_tpu.models.generate import beam_search

        cfg = _tiny_cfg()
        model, params, prompt = _init(cfg)
        with pytest.raises(ValueError, match="pad_id"):
            beam_search(model, params, prompt, 4, num_beams=2, pad_id=-1)

    def test_length_penalty_does_not_recompile(self):
        from dmlcloud_tpu.models.generate import _beam_search_compiled, beam_search

        cfg = _tiny_cfg()
        model, params, prompt = _init(cfg)
        beam_search(model, params, prompt, 3, num_beams=2, length_penalty=0.7)
        misses = _beam_search_compiled._cache_size()
        beam_search(model, params, prompt, 3, num_beams=2, length_penalty=1.3)
        assert _beam_search_compiled._cache_size() == misses


class TestRaggedPrompts:
    @pytest.mark.slow
    def test_left_padded_rows_match_unpadded(self):
        """Each left-padded row must decode exactly as its unpadded self."""
        cfg = _tiny_cfg()
        model, params, _ = _init(cfg)
        rng = np.random.RandomState(11)
        p1 = rng.randint(1, 61, size=5)
        p2 = rng.randint(1, 61, size=9)
        t = 9
        batch = np.zeros((2, t), np.int32)
        mask = np.zeros((2, t), np.int32)
        batch[0, t - 5 :], mask[0, t - 5 :] = p1, 1
        batch[1, :], mask[1, :] = p2, 1

        got = generate(model, params, jnp.asarray(batch), 6, prompt_mask=jnp.asarray(mask))
        want1 = generate(model, params, jnp.asarray(p1[None]), 6)
        want2 = generate(model, params, jnp.asarray(p2[None]), 6)
        np.testing.assert_array_equal(np.asarray(got)[0], np.asarray(want1)[0])
        np.testing.assert_array_equal(np.asarray(got)[1], np.asarray(want2)[0])

    def test_windowed_ragged(self):
        cfg = _tiny_cfg(sliding_window=4)
        model, params, _ = _init(cfg)
        rng = np.random.RandomState(12)
        p1 = rng.randint(1, 61, size=3)
        p2 = rng.randint(1, 61, size=7)
        t = 7
        batch = np.zeros((2, t), np.int32)
        mask = np.zeros((2, t), np.int32)
        batch[0, t - 3 :], mask[0, t - 3 :] = p1, 1
        batch[1, :], mask[1, :] = p2, 1
        got = generate(model, params, jnp.asarray(batch), 5, prompt_mask=jnp.asarray(mask))
        want1 = generate(model, params, jnp.asarray(p1[None]), 5)
        np.testing.assert_array_equal(np.asarray(got)[0], np.asarray(want1)[0])

    def test_right_padding_rejected(self):
        cfg = _tiny_cfg()
        model, params, prompt = _init(cfg)
        mask = np.ones((2, 7), np.int32)
        mask[:, -2:] = 0  # right padding
        with pytest.raises(ValueError, match="LEFT"):
            generate(model, params, prompt, 4, prompt_mask=mask)

    def test_right_padding_rejected_for_jax_arrays_too(self):
        cfg = _tiny_cfg()
        model, params, prompt = _init(cfg)
        mask = np.ones((2, 7), np.int32)
        mask[:, -2:] = 0
        with pytest.raises(ValueError, match="LEFT"):
            generate(model, params, prompt, 4, prompt_mask=jnp.asarray(mask))

    def test_bad_mask_shape_message(self):
        cfg = _tiny_cfg()
        model, params, prompt = _init(cfg)
        with pytest.raises(ValueError, match=r"\[B, T\]"):
            generate(model, params, prompt, 4, prompt_mask=np.ones(7, np.int32))


@pytest.mark.slow
def test_ragged_beam_rows_match_unpadded():
    from dmlcloud_tpu.models.generate import beam_search

    cfg = _tiny_cfg()
    model, params, _ = _init(cfg)
    rng = np.random.RandomState(13)
    p1 = rng.randint(1, 61, size=4)
    p2 = rng.randint(1, 61, size=8)
    t = 8
    batch, mask = np.zeros((2, t), np.int32), np.zeros((2, t), np.int32)
    batch[0, t - 4 :], mask[0, t - 4 :] = p1, 1
    batch[1], mask[1] = p2, 1

    got, scores = beam_search(model, params, jnp.asarray(batch), 5, num_beams=3,
                              prompt_mask=jnp.asarray(mask))
    want1, s1 = beam_search(model, params, jnp.asarray(p1[None]), 5, num_beams=3)
    want2, s2 = beam_search(model, params, jnp.asarray(p2[None]), 5, num_beams=3)
    np.testing.assert_array_equal(np.asarray(got)[0], np.asarray(want1)[0])
    np.testing.assert_array_equal(np.asarray(got)[1], np.asarray(want2)[0])
    np.testing.assert_allclose(np.asarray(scores), [float(s1[0]), float(s2[0])], atol=1e-5)


def test_rewind_cache_masks_exactly():
    """rewind_cache is ONE masked select over the tree: slots at position
    >= fill_len zero out, slots below are untouched bit for bit — with a
    per-row [B] fill, a scalar fill, and under jit (traced fill)."""
    from dmlcloud_tpu.models.generate import rewind_cache

    rng = np.random.RandomState(0)
    cache = {
        "layer_0": {
            "k": jnp.asarray(rng.randn(2, 16, 1, 4), jnp.float32),
            "v": jnp.asarray(rng.randn(2, 16, 1, 4), jnp.float32),
        }
    }
    fill = jnp.asarray([5, 11], jnp.int32)
    for rewound in (rewind_cache(cache, fill), jax.jit(rewind_cache)(cache, fill)):
        for name in ("k", "v"):
            got = np.asarray(rewound["layer_0"][name])
            want = np.asarray(cache["layer_0"][name]).copy()
            want[0, 5:] = 0
            want[1, 11:] = 0
            np.testing.assert_array_equal(got, want)
    # scalar fill broadcasts to every row
    got = np.asarray(rewind_cache(cache, 3)["layer_0"]["k"])
    assert (got[:, 3:] == 0).all()
    np.testing.assert_array_equal(got[:, :3], np.asarray(cache["layer_0"]["k"])[:, :3])


def test_attend_len_bounds_cache_reads():
    """With attend_len set, slots past it must never be READ: poison the
    cache tail with NaN and the logits must stay finite and equal to the
    clean-cache result. This is the property that makes decode cost scale
    with fill instead of max_len."""
    model, params, prompt = _init(_tiny_cfg(), batch=2, t=8)
    cache = init_cache(model.cfg, 2, 32, dtype=model.cfg.dtype)
    clean, _ = model.apply({"params": params}, prompt, cache=cache, offset=0, attend_len=8)
    poisoned = jax.tree_util.tree_map(lambda x: x.at[:, 8:].set(jnp.nan), cache)
    got, new_cache = model.apply({"params": params}, prompt, cache=poisoned, offset=0, attend_len=8)
    assert bool(jnp.all(jnp.isfinite(got)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(clean), rtol=1e-6, atol=1e-6)
    # the returned cache is still the FULL buffer (writes are never bounded)
    assert new_cache["layer_0"]["k"].shape[1] == 32


@pytest.mark.slow
def test_long_generation_exercises_multi_step_segments():
    """max_new_tokens > _DECODE_CHUNKS forces scan segments longer than one
    step, where attend_len runs AHEAD of the fill inside a segment — greedy
    must still match the no-cache reference and single-beam greedy."""
    from dmlcloud_tpu.models.generate import _DECODE_CHUNKS, beam_search

    n = 2 * _DECODE_CHUNKS + 4  # segment length >= 3
    model, params, prompt = _init(_tiny_cfg(max_seq_len=64), batch=2, t=6)
    got = generate(model, params, prompt, max_new_tokens=n)
    want = _greedy_no_cache(model, params, prompt, n)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    beam_toks, _ = beam_search(model, params, prompt, max_new_tokens=n, num_beams=1)
    np.testing.assert_array_equal(np.asarray(beam_toks), np.asarray(got))


class TestBatchedSampler:
    """sample_logits_batched: the per-row traced twin of sample_logits
    (the serving engine's mixed-tenant sampling path)."""

    def _logits(self, b=4, v=61, seed=6, scale=3.0):
        return jax.random.normal(jax.random.PRNGKey(seed), (b, v)) * scale

    @pytest.mark.parametrize(
        "t,k,p",
        [(0.0, 0, 1.0), (0.7, 0, 1.0), (1.0, 10, 1.0), (0.9, 0, 0.7), (1.2, 5, 0.9)],
    )
    def test_uniform_rows_match_scalar_sampler(self, t, k, p):
        """A batch whose rows all share one param set must sample the SAME
        tokens as the scalar sampler with those params (same rng, same
        truncation, same categorical)."""
        from dmlcloud_tpu.models.generate import sample_logits, sample_logits_batched

        logits = self._logits()
        rng = jax.random.PRNGKey(5)
        a = sample_logits(logits, rng, t, k, p)
        b = sample_logits_batched(
            logits, rng,
            jnp.full(4, t, jnp.float32), jnp.full(4, k, jnp.int32), jnp.full(4, p, jnp.float32),
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_mixed_rows_greedy_is_exact_argmax(self):
        """Rows with temperature 0 in a mixed batch return the exact
        argmax regardless of the other rows' params."""
        from dmlcloud_tpu.models.generate import sample_logits_batched

        logits = self._logits()
        out = sample_logits_batched(
            logits, jax.random.PRNGKey(0),
            jnp.asarray([0.0, 1.5, 0.0, 0.8]),
            jnp.asarray([0, 5, 0, 0], jnp.int32),
            jnp.asarray([1.0, 1.0, 1.0, 0.6]),
        )
        greedy = np.argmax(np.asarray(logits), axis=-1)
        assert int(out[0]) == greedy[0] and int(out[2]) == greedy[2]

    def test_top_k_truncation_is_per_row(self):
        """top_k=1 rows must return the argmax (only one candidate
        survives) even at high temperature; top_k=0 rows stay untruncated."""
        from dmlcloud_tpu.models.generate import sample_logits_batched

        logits = self._logits(b=3)
        out = sample_logits_batched(
            logits, jax.random.PRNGKey(1),
            jnp.asarray([5.0, 5.0, 5.0]),
            jnp.asarray([1, 1, 0], jnp.int32),
            jnp.ones(3, jnp.float32),
        )
        greedy = np.argmax(np.asarray(logits), axis=-1)
        assert int(out[0]) == greedy[0] and int(out[1]) == greedy[1]

    def test_top_p_tiny_nucleus_is_argmax(self):
        """top_p small enough keeps only the head of the distribution —
        with a dominant logit the sample is forced to the argmax."""
        from dmlcloud_tpu.models.generate import sample_logits_batched

        logits = jnp.zeros((2, 8)).at[:, 3].set(10.0)
        out = sample_logits_batched(
            logits, jax.random.PRNGKey(2),
            jnp.asarray([1.0, 1.0]), jnp.zeros(2, jnp.int32), jnp.asarray([0.1, 0.1]),
        )
        np.testing.assert_array_equal(np.asarray(out), [3, 3])
