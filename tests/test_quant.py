"""Weight-only int8 quantization: per-channel error bounds, tree matching,
size accounting, and quantized decode through the real generate path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.models.quant import (
    QuantizedTensor,
    dequant_tree,
    quantize,
    quantize_tree,
    quantized_size,
)


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    w = rng.randn(64, 32).astype(np.float32) * np.logspace(-2, 0, 32)  # per-channel ranges
    qt = quantize(jnp.asarray(w))
    back = np.asarray(qt.dequant(jnp.float32))
    # symmetric int8: error <= scale/2 per element, scale = col_max/127
    col_max = np.abs(w).max(axis=0)
    assert (np.abs(back - w) <= col_max / 127.0 / 2 + 1e-7).all()
    # per-channel beats per-tensor by construction on ranged columns
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 32)


def test_quantize_zero_channel_safe():
    w = jnp.zeros((8, 4))
    qt = quantize(w)
    np.testing.assert_array_equal(np.asarray(qt.dequant(jnp.float32)), 0.0)


def test_quantize_tree_matches_kernels_only():
    params = {
        "dense": {"kernel": jnp.ones((8, 4)), "bias": jnp.ones(4)},
        "embed": {"embedding": jnp.ones((100, 8))},
        "norm": {"scale": jnp.ones(8)},
    }
    qtree = quantize_tree(params)
    assert isinstance(qtree["dense"]["kernel"], QuantizedTensor)
    assert not isinstance(qtree["embed"]["embedding"], QuantizedTensor)
    assert not isinstance(qtree["norm"]["scale"], QuantizedTensor)
    # dequant restores plain arrays everywhere
    back = dequant_tree(qtree, jnp.float32)
    assert all(
        isinstance(x, jax.Array) for x in jax.tree_util.tree_leaves(back)
    )
    q_bytes, full_bytes = quantized_size(qtree)
    assert q_bytes < full_bytes  # int8 kernels beat bf16 kernels


# quant_lm (the 64-vocab decode LM) comes from conftest.py, session-scoped.


@pytest.mark.slow
def test_quantized_generate_matches_shapes_and_tracks_full(quant_lm):
    from dmlcloud_tpu.models.generate import generate

    model, params = quant_lm
    prompt = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 8)), jnp.int32)
    full = np.asarray(generate(model, params, prompt, max_new_tokens=12))
    qparams = quantize_tree(params)
    quant = np.asarray(generate(model, qparams, prompt, max_new_tokens=12))
    assert quant.shape == full.shape == (2, 12)
    # int8 weights perturb logits slightly; greedy tokens should still
    # mostly agree on a tiny random model (identical for the vast majority
    # of positions; an occasional near-tie may flip)
    agreement = (quant == full).mean()
    assert agreement >= 0.75, (agreement, quant, full)


def test_quantized_logits_close_to_full(quant_lm):
    model, params = quant_lm
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, 64, (2, 16)), jnp.int32)
    full = np.asarray(model.apply({"params": params}, tokens))
    deq = dequant_tree(quantize_tree(params), jnp.float32)
    quant = np.asarray(model.apply({"params": deq}, tokens))
    denom = np.abs(full).max()
    assert np.abs(quant - full).max() / denom < 0.05


def test_prepare_decode_params_is_exact_and_stays_quantized(quant_lm):
    """prepare_decode_params pre-pays the off-TPU GEMM-operand widen ONCE:
    kernels stay QuantizedTensor (scales still applied to the accumulator
    in the fused dot), q widens to fp32 exactly (int8 -> fp32 is lossless),
    and decode output is bit-identical to passing the raw int8 tree."""
    from dmlcloud_tpu.models.generate import generate
    from dmlcloud_tpu.models.quant import prepare_decode_params

    model, params = quant_lm
    qparams = quantize_tree(params)
    prepared = prepare_decode_params(qparams, jnp.float32)

    is_qt = lambda x: isinstance(x, QuantizedTensor)
    q_leaves = [x for x in jax.tree_util.tree_leaves(prepared, is_leaf=is_qt) if is_qt(x)]
    raw_leaves = [x for x in jax.tree_util.tree_leaves(qparams, is_leaf=is_qt) if is_qt(x)]
    assert q_leaves, "prepared tree lost its quantized kernels"
    assert len(q_leaves) == len(raw_leaves)
    for wide, raw in zip(q_leaves, raw_leaves):
        assert wide.q.dtype == jnp.float32  # off-TPU operand dtype (CPU CI)
        np.testing.assert_array_equal(np.asarray(wide.q), np.asarray(raw.q, np.float32))
        np.testing.assert_array_equal(np.asarray(wide.scale), np.asarray(raw.scale))

    prompt = jnp.asarray(np.random.RandomState(3).randint(0, 64, (2, 6)), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(generate(model, qparams, prompt, max_new_tokens=8)),
        np.asarray(generate(model, prepared, prompt, max_new_tokens=8)),
    )


def test_widen_quant_tree_inside_jit_matches_per_step_path():
    """The in-program widen (decode entry points call it before the loop)
    must be a pure layout change: same QuantizedTensor structure, same
    values, fp32 q — and non-quantized leaves pass through untouched."""
    from dmlcloud_tpu.models.quant import widen_quant_tree

    rng = np.random.RandomState(4)
    tree = {
        "dense": {"kernel": quantize(jnp.asarray(rng.randn(16, 8), jnp.float32))},
        "bias": jnp.asarray(rng.randn(8), jnp.float32),
    }
    out = jax.jit(widen_quant_tree)(tree)
    assert isinstance(out["dense"]["kernel"], QuantizedTensor)
    assert out["dense"]["kernel"].q.dtype == jnp.float32
    np.testing.assert_array_equal(
        np.asarray(out["dense"]["kernel"].q), np.asarray(tree["dense"]["kernel"].q, np.float32)
    )
    np.testing.assert_array_equal(np.asarray(out["bias"]), np.asarray(tree["bias"]))
