"""step_flops() -> automatic misc/mfu tracking from measured step time and
the mesh's aggregate chip peak."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import dmlcloud_tpu as dml
from dmlcloud_tpu.utils import profiling


class _FlopsStage(dml.TrainValStage):
    def step_flops(self):
        return 1.0e9

    def pre_stage(self):
        import flax.linen as nn

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(1, use_bias=False)(x)

        model = Lin()
        self.pipeline.register_model(
            "lin", model, params=model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4))),
            verbose=False,
        )
        self.pipeline.register_optimizer("sgd", optax.sgd(0.1))
        x = np.ones((16, 4), np.float32)
        self.pipeline.register_dataset("train", [{"x": x, "y": x.sum(1, keepdims=True)}] * 4, verbose=False)

    def step(self, state, batch):
        pred = state.apply_fn({"params": state.params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    def val_epoch(self):
        pass


def test_mfu_tracked_per_epoch(monkeypatch):
    # give the CPU device kind an entry so the metric is tracked here the
    # way it would be on a real chip
    kind = jax.local_devices()[0].device_kind.lower()
    monkeypatch.setitem(profiling.PEAK_BF16_FLOPS, kind, 197e12)
    pipe = dml.TrainingPipeline(name="mfu-test")
    stage = _FlopsStage()
    pipe.append_stage(stage, max_epochs=2)
    pipe.run()
    hist = stage.tracker["misc/mfu"]
    assert len(hist) == 2 and all(v is not None and v > 0 for v in hist)
    # consistency: mfu == flops/step / step_time / total_peak
    step_ms = stage.tracker["misc/train_step_avg_ms"][-1]
    peak_total = profiling.chip_peak_flops() * int(pipe.mesh.devices.size)
    expected = 1.0e9 / (step_ms / 1e3) / peak_total
    np.testing.assert_allclose(hist[-1], expected, rtol=1e-6)


def test_mfu_skipped_on_unknown_device_kind():
    # CPU (and any backend outside the bf16 peak table) gets NO misc/mfu
    # rather than a number computed against a made-up TPU peak
    if profiling.peak_flops_for_kind(jax.local_devices()[0].device_kind) is not None:
        import pytest

        pytest.skip("running on a device with a known peak; skip path untestable")
    pipe = dml.TrainingPipeline(name="mfu-unknown")
    stage = _FlopsStage()
    pipe.append_stage(stage, max_epochs=1)
    pipe.run()
    assert "misc/mfu" not in stage.tracker
    assert stage.tracker["misc/train_step_avg_ms"]  # step timing still tracked


def test_mfu_absent_when_disabled():
    class Off(_FlopsStage):
        def step_flops(self):
            return 0.0

    pipe = dml.TrainingPipeline(name="mfu-off")
    stage = Off()
    pipe.append_stage(stage, max_epochs=1)
    pipe.run()
    assert "misc/mfu" not in stage.tracker
