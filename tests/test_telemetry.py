"""Flight recorder & goodput telemetry (dmlcloud_tpu.telemetry).

Covers: journal schema v1 (LOCKED — a change here is a schema bump, not an
edit), ring/flush mechanics, the multi-rank Chrome-trace merge and its CLI,
an end-to-end CPU pipeline run with ``telemetry=True`` (bucket times must
sum to the epoch wall time), the goodput ledger, and the hang watchdog's
forensics dump — including the barrier-straggler integration: a timed-out
barrier must leave the non-arriving ranks where the dump can name them.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import dmlcloud_tpu as dml
from dmlcloud_tpu.__main__ import main as cli_main
from dmlcloud_tpu.parallel import runtime
from dmlcloud_tpu.telemetry import (
    SCHEMA_VERSION,
    SPAN_KINDS,
    HangWatchdog,
    SpanJournal,
    journal as journal_mod,
    ledger_from_tracker,
    load_journals,
    to_chrome_trace,
)
from dmlcloud_tpu.telemetry.goodput import flops_from_compiled

# ---------------------------------------------------------------------------
# schema v1 lock
# ---------------------------------------------------------------------------

#: The locked v1 vocabulary. Adding a kind is a PR-visible edit HERE;
#: renaming or removing one requires a schema version bump.
V1_KINDS = {
    "run", "stage", "epoch", "step_dispatch", "data_wait", "h2d",
    "metric_readback", "checkpoint", "barrier", "compile", "host_stall",
    "watchdog", "sanitizer",
    # serving engine (PR 8): queue wait, chunked prefill, decode batches
    "queue_wait", "prefill", "decode_batch",
    # speculative serving (PR 10): draft-model calls, verification passes
    "draft", "verify",
    # overload control (PR 13): isolated step failures, graceful drain
    "fault", "drain",
    # multi-replica router (PR 15): placement, dead-replica resubmission,
    # router-coordinated drain of one replica
    "route", "failover", "replica_drain",
    # Medusa decoding (PR 16): draftless speculative rounds
    "medusa",
    # observability plane (PR 19): admission into a decode slot, prefix
    # cache lookups, copy-on-write forks, SLO burn-rate alerts
    "admission", "prefix_lookup", "cow_fork", "slo_alert",
    # IR-level verifier (PR 20): one traced/audited program per span
    # (named "preflight" because "verify" was already the spec-decode
    # verification pass)
    "preflight",
}

#: Core fields every v1 record carries, with their types.
V1_FIELDS = {"v": int, "kind": str, "ts": float, "dur": float, "rank": int, "tid": str}


class TestSchemaV1:
    def test_version_and_kinds_locked(self):
        assert SCHEMA_VERSION == 1
        assert SPAN_KINDS == frozenset(V1_KINDS)

    def test_record_fields_locked(self, tmp_path):
        j = SpanJournal(tmp_path, rank=3)
        t0 = j.now()
        rec = j.emit("step_dispatch", t0, t0 + 0.001, label="x", step=7)
        for field, typ in V1_FIELDS.items():
            assert field in rec, f"v1 record lost core field {field!r}"
            assert isinstance(rec[field], typ), (field, rec[field])
        assert rec["v"] == 1
        assert rec["rank"] == 3
        assert rec["label"] == "x"
        assert rec["step"] == 7  # attrs ride as extra keys
        assert rec["dur"] == pytest.approx(0.001, abs=1e-6)

    def test_round_trips_through_jsonl(self, tmp_path):
        j = SpanJournal(tmp_path, rank=0)
        t0 = j.now()
        j.emit("epoch", t0, t0 + 0.5, label="TrainValStage", epoch=2)
        j.close()
        lines = (tmp_path / "journal-rank0.jsonl").read_text().splitlines()
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["kind"] == "epoch" and rec["epoch"] == 2 and rec["v"] == 1


class TestJournal:
    def test_ring_keeps_last_n(self, tmp_path):
        j = SpanJournal(tmp_path, ring_size=8)
        t = j.now()
        for i in range(20):
            j.emit("step_dispatch", t, t, step=i)
        tail = j.tail(5)
        assert [r["step"] for r in tail] == [15, 16, 17, 18, 19]
        assert len(j) == 8  # ring bounded even though 20 were emitted

    def test_flush_is_incremental_and_complete(self, tmp_path):
        j = SpanJournal(tmp_path)
        t = j.now()
        j.emit("data_wait", t, t)
        assert j.flush() == 1
        j.emit("data_wait", t, t)
        j.emit("h2d", t, t)
        assert j.flush() == 2
        assert j.flush() == 0
        j.close()
        assert len((tmp_path / "journal-rank0.jsonl").read_text().splitlines()) == 3

    def test_background_flusher_writes_without_close(self, tmp_path):
        j = SpanJournal(tmp_path, flush_interval=0.05).start()
        t = j.now()
        j.emit("barrier", t, t, label="x")
        deadline = time.perf_counter() + 5.0
        path = tmp_path / "journal-rank0.jsonl"
        while time.perf_counter() < deadline:
            if path.read_text().strip():
                break
            time.sleep(0.02)
        j.close()
        assert path.read_text().strip(), "flusher thread never wrote the pending span"

    def test_span_ctx_manager_and_on_emit(self, tmp_path):
        j = SpanJournal(tmp_path)
        pings = []
        j.on_emit = lambda: pings.append(1)
        with j.span("compile", label="train_step"):
            pass
        assert pings == [1]
        assert j.tail(1)[0]["kind"] == "compile"

    def test_module_level_noop_when_inactive(self):
        assert journal_mod.active_journal() is None
        with journal_mod.span("h2d"):  # must not raise, must not record
            pass
        assert journal_mod.emit("h2d", 0.0, 1.0) is None

    def test_emit_thread_name_rides_tid(self, tmp_path):
        j = SpanJournal(tmp_path)
        out = {}

        def worker():
            t = j.now()
            out["rec"] = j.emit("h2d", t, t)

        th = threading.Thread(target=worker, name="prefetcher")
        th.start()
        th.join()
        assert out["rec"]["tid"] == "prefetcher"


class TestChromeTrace:
    def _write_journal(self, d, rank, n=3):
        j = SpanJournal(d, rank=rank)
        t = j.now()
        for i in range(n):
            j.emit("step_dispatch", t + i * 0.01, t + i * 0.01 + 0.005, step=i)
        j.emit("epoch", t, t + n * 0.01, label="stage", epoch=1)
        j.close()

    def test_merges_ranks_into_one_trace(self, tmp_path):
        tdir = tmp_path / "telemetry"
        self._write_journal(tdir, rank=0)
        self._write_journal(tdir, rank=1)
        records = load_journals(tmp_path)  # accepts the run dir
        assert {r["rank"] for r in records} == {0, 1}
        trace = to_chrome_trace(records)
        events = trace["traceEvents"]
        x = [e for e in events if e["ph"] == "X"]
        assert len(x) == 8  # 4 spans per rank
        assert {e["pid"] for e in x} == {0, 1}
        for e in x:
            assert isinstance(e["tid"], int)
            assert e["ts"] >= 0.0 and e["dur"] >= 0.0
        names = {e["args"]["name"] for e in events if e["name"] == "process_name"}
        assert names == {"rank 0", "rank 1"}
        # rebased to the earliest span so the viewer opens at t=0
        assert min(e["ts"] for e in x) == 0.0

    def test_missing_journals_is_a_clear_error(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="telemetry"):
            load_journals(tmp_path / "nope")

    def test_truncated_trailing_line_skipped(self, tmp_path):
        tdir = tmp_path / "telemetry"
        self._write_journal(tdir, rank=0, n=2)
        with open(tdir / "journal-rank0.jsonl", "a") as f:
            f.write('{"v": 1, "kind": "step_dis')  # killed mid-write
        records = load_journals(tmp_path)
        assert len(records) == 3

    def test_timeline_cli(self, tmp_path, capsys):
        self._write_journal(tmp_path / "telemetry", rank=0)
        out_file = tmp_path / "trace.json"
        rc = cli_main(["timeline", str(tmp_path), "-o", str(out_file)])
        assert rc == 0
        trace = json.loads(out_file.read_text())
        assert trace["traceEvents"] and trace["metadata"]["schema"] == 1
        # stdout mode emits the JSON itself
        rc = cli_main(["timeline", str(tmp_path)])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["traceEvents"]

    def test_timeline_cli_without_journals(self, tmp_path, capsys):
        rc = cli_main(["timeline", str(tmp_path)])
        assert rc == 1
        assert "telemetry" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# end-to-end: CPU pipeline run with telemetry=True
# ---------------------------------------------------------------------------


class _TeleStage(dml.TrainValStage):
    def __init__(self, batches):
        super().__init__()
        self._batches = batches

    def pre_stage(self):
        import flax.linen as nn

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(1)(x)

        model = MLP()
        self.pipeline.register_model(
            "m", model, params=model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8))), verbose=False
        )
        self.pipeline.register_optimizer("sgd", optax.sgd(0.01))
        self.pipeline.register_dataset("train", self._batches, verbose=False)

    def step(self, state, batch):
        pred = state.apply_fn({"params": state.params}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    def log_every(self):
        return 5


def _batches(n=12, b=16, d=8):
    rng = np.random.RandomState(0)
    w = rng.randn(d, 1).astype(np.float32)
    xs = rng.randn(n, b, d).astype(np.float32)
    return [{"x": x, "y": x @ w} for x in xs]


@pytest.fixture
def tele_run(tmp_path, single_runtime):
    pipeline = dml.TrainingPipeline(name="tele", telemetry=True)
    pipeline.append_stage(_TeleStage(_batches()), max_epochs=2)
    pipeline.enable_checkpointing(str(tmp_path))
    pipeline.run()
    return pipeline


class TestPipelineTelemetry:
    def test_journal_written_and_timeline_converts(self, tele_run):
        run_dir = str(tele_run.checkpoint_dir.path)
        records = load_journals(run_dir)
        kinds = {r["kind"] for r in records}
        # the instrumentation points the tentpole wires up, all firing
        for expected in ("run", "stage", "epoch", "step_dispatch", "data_wait", "h2d", "checkpoint"):
            assert expected in kinds, f"no {expected!r} spans in the journal"
        assert all(r["v"] == 1 for r in records)
        trace = to_chrome_trace(records)
        json.dumps(trace)  # valid, serializable Chrome-trace JSON
        assert any(e.get("cat") == "epoch" for e in trace["traceEvents"])
        # two epochs ran -> two epoch spans
        assert sum(1 for r in records if r["kind"] == "epoch") == 2

    def test_goodput_buckets_sum_to_epoch_time(self, tele_run):
        tracker = tele_run.tracker
        epochs = tracker["misc/epoch_time"]
        data_wait = tracker["misc/data_wait_ms"]
        ckpt = tracker["misc/ckpt_ms"]
        stall = tracker["misc/host_stall_ms"]
        goodput = tracker["misc/goodput"]
        assert len(goodput) == 2
        for i, epoch_s in enumerate(epochs):
            productive = float(goodput[i]) * float(epoch_s)
            other = (float(data_wait[i]) + float(stall[i])) / 1e3
            # disjoint buckets (ckpt is inside stall) must reassemble the
            # epoch wall time — the acceptance bound is 5%
            assert productive + other == pytest.approx(float(epoch_s), rel=0.05)
            assert float(ckpt[i]) <= float(stall[i]) + 1e-6

    def test_ledger_and_goodput_json(self, tele_run):
        ledger = ledger_from_tracker(tele_run.tracker)
        assert len(ledger.rows) == 2
        totals = ledger.totals()
        assert 0.0 < totals["goodput_frac"] <= 1.0
        table = ledger.format_table()
        assert "goodput" in table and "data_wait" in table
        gp = json.loads((tele_run.checkpoint_dir.path / "telemetry" / "goodput.json").read_text())
        assert gp["v"] == 1
        assert gp["totals"]["epochs"] == 2
        for row in gp["epochs"]:
            bucket_sum = row["data_wait_s"] + row["ckpt_s"] + row["stall_s"] + row["productive_s"]
            assert bucket_sum == pytest.approx(row["epoch_s"], rel=0.05)

    def test_disarmed_after_run(self, tele_run):
        assert not tele_run.telemetry_armed
        assert journal_mod.active_journal() is None

    def test_diag_run_summary(self, tele_run, capsys):
        rc = cli_main(["diag", "--json", "--run", str(tele_run.checkpoint_dir.path)])
        assert rc == 0
        info = json.loads(capsys.readouterr().out)
        assert info["telemetry"]["goodput"]["epochs"] == 2
        assert info["telemetry"]["journal"]["spans"] > 0
        assert "step_dispatch" in info["telemetry"]["journal"]["kinds"]

    def test_telemetry_off_by_default(self, tmp_path, single_runtime):
        pipeline = dml.TrainingPipeline(name="off")
        pipeline.append_stage(_TeleStage(_batches(n=4)), max_epochs=1)
        pipeline.enable_checkpointing(str(tmp_path))
        pipeline.run()
        assert not (pipeline.checkpoint_dir.path / "telemetry").exists()
        assert "misc/goodput" not in pipeline.tracker

    def test_invalid_telemetry_arg_rejected(self):
        with pytest.raises(ValueError, match="telemetry"):
            dml.TrainingPipeline(telemetry=3.14)


# ---------------------------------------------------------------------------
# goodput unit coverage
# ---------------------------------------------------------------------------


class TestGoodputLedger:
    def _tracker(self):
        from dmlcloud_tpu.metrics import MetricTracker, Reduction

        t = MetricTracker()
        for name in ("misc/epoch_time", "misc/data_wait_ms", "misc/ckpt_ms",
                     "misc/host_stall_ms", "misc/goodput"):
            t.register_metric(name)
        for epoch_s, dw, ck, st in ((10.0, 1000.0, 500.0, 1500.0), (8.0, 800.0, 0.0, 200.0)):
            t.track("misc/epoch_time", epoch_s)
            t.track("misc/data_wait_ms", dw)
            t.track("misc/ckpt_ms", ck)
            t.track("misc/host_stall_ms", st)
            t.track("misc/goodput", (epoch_s - (dw + st) / 1e3) / epoch_s)
            t.next_epoch()
        return t

    def test_rows_and_totals(self):
        ledger = ledger_from_tracker(self._tracker())
        assert len(ledger.rows) == 2
        r = ledger.rows[0]
        assert r["epoch_s"] == 10.0
        assert r["data_wait_s"] == 1.0
        assert r["ckpt_s"] == 0.5
        assert r["stall_s"] == 1.0  # host_stall minus the ckpt share
        assert r["productive_s"] == pytest.approx(7.5)
        totals = ledger.totals()
        assert totals["wall_s"] == pytest.approx(18.0)
        assert totals["productive_s"] == pytest.approx(7.5 + 7.0)
        assert totals["goodput_frac"] == pytest.approx(14.5 / 18.0, rel=1e-3)

    def test_empty_tracker(self):
        from dmlcloud_tpu.metrics import MetricTracker

        ledger = ledger_from_tracker(MetricTracker())
        assert ledger.rows == []
        assert ledger.totals()["goodput_frac"] is None

    def test_flops_from_compiled(self):
        class FakeCompiled:
            def cost_analysis(self):
                return {"flops": 2.5e9}

        class Broken:
            def cost_analysis(self):
                raise RuntimeError("no analysis on this backend")

        assert flops_from_compiled(FakeCompiled(), n_devices=4) == 1e10
        assert flops_from_compiled(Broken()) is None
        class Listy:
            def cost_analysis(self):
                return [{"flops": 5.0}]

        assert flops_from_compiled(Listy()) == 5.0


# ---------------------------------------------------------------------------
# hang watchdog + forensics
# ---------------------------------------------------------------------------


class _FakeClient:
    """Same stub as test_runtime's: arrival keys + scripted wait error."""

    def __init__(self, wait_error=None):
        self.kv = {}
        self.wait_error = wait_error

    def key_value_set(self, key, value):
        self.kv[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        if key in self.kv:
            return self.kv[key]
        raise RuntimeError("DEADLINE_EXCEEDED: key not found")

    def key_value_delete(self, key):
        self.kv.pop(key, None)

    def wait_at_barrier(self, barrier_id, timeout_in_ms):
        if self.wait_error is not None:
            raise self.wait_error


class TestWatchdog:
    def _watchdog(self, tmp_path, journal=None, threshold=10.0):
        clock = {"t": 100.0}
        wd = HangWatchdog(
            tmp_path / "forensics",
            rank=0,
            world_size=4,
            threshold_s=threshold,
            journal=journal,
            clock=lambda: clock["t"],
        )
        return wd, clock

    def test_no_dump_below_threshold(self, tmp_path):
        wd, clock = self._watchdog(tmp_path)
        clock["t"] += 9.0
        assert wd.check() is None
        assert not (tmp_path / "forensics").exists()

    def test_dump_once_per_stall_and_rearm(self, tmp_path):
        wd, clock = self._watchdog(tmp_path)
        clock["t"] += 11.0
        path = wd.check()
        assert path is not None
        assert wd.check() is None  # same stall: no dump storm
        wd.notify()
        clock["t"] += 11.0
        assert wd.check() is not None  # new stall after progress re-arms

    def test_dump_contents(self, tmp_path):
        j = SpanJournal(tmp_path / "telemetry", rank=0, ring_size=16)
        t = j.now()
        for i in range(20):
            j.emit("step_dispatch", t, t, step=i)
        wd, clock = self._watchdog(tmp_path, journal=j, threshold=5.0)
        clock["t"] += 6.0
        path = wd.check()
        dump = json.loads(open(path).read())
        assert dump["v"] == 1
        assert dump["rank"] == 0 and dump["world_size"] == 4
        assert "no span/step progress" in dump["reason"]
        assert dump["last_progress_age_s"] == pytest.approx(6.0)
        # last-N spans from the ring (bounded by ring_size=16)
        assert [r["step"] for r in dump["spans"]] == list(range(4, 20))
        # every live thread's stack, this test's own frame included
        me = [th for th in dump["threads"] if th["name"] == threading.current_thread().name]
        assert me and any("test_telemetry" in line for line in me[0]["stack"])
        j.close()

    def test_barrier_straggler_feeds_forensics(self, tmp_path, single_runtime, monkeypatch):
        """The acceptance path: a barrier that times out records the ranks
        that never arrived, and the watchdog's dump names them."""
        client = _FakeClient(wait_error=RuntimeError("DEADLINE_EXCEEDED while waiting"))
        monkeypatch.setattr(runtime, "_client", lambda: client)
        monkeypatch.setattr(runtime, "world_size", lambda: 4)
        monkeypatch.setattr(runtime, "rank", lambda: 0)
        j = SpanJournal(tmp_path / "telemetry", rank=0)
        journal_mod.activate(j)
        try:
            with pytest.raises(runtime.BarrierTimeout):
                runtime.barrier("epoch_end", timeout=1)
        finally:
            journal_mod.deactivate()
        wd, clock = self._watchdog(tmp_path, journal=j, threshold=5.0)
        clock["t"] += 6.0
        dump = json.loads(open(wd.check()).read())
        # the stuck ranks, by name: this rank arrived, 1..3 never did
        assert dump["barrier"]["status"] == "timeout"
        assert dump["barrier"]["stragglers"] == [1, 2, 3]
        assert dump["barrier"]["tag"] == "epoch_end"
        # the timed-out barrier also journaled a span for the timeline
        barrier_spans = [r for r in j.tail(64) if r["kind"] == "barrier"]
        assert barrier_spans and barrier_spans[-1]["status"] == "timeout"
        assert barrier_spans[-1]["stragglers"] == [1, 2, 3]
        j.close()

    def test_stalled_step_triggers_dump(self, tmp_path, single_runtime):
        """Acceptance: a mocked stalled step (the feed hangs mid-epoch) makes
        the real watchdog thread dump forensics naming this rank."""

        def stalling_batches():
            for i, b in enumerate(_batches(n=6)):
                if i == 3:
                    time.sleep(1.0)  # the "hang": 4x the threshold
                yield b

        class StallingStage(_TeleStage):
            def pre_stage(self):
                super().pre_stage()
                self.pipeline.datasets["train"] = stalling_batches()

        pipeline = dml.TrainingPipeline(
            name="hang",
            telemetry={
                "dir": str(tmp_path / "tele"),
                "hang_threshold_s": 0.25,
                "watchdog_interval_s": 0.05,
            },
        )
        pipeline.append_stage(StallingStage(_batches(n=6)), max_epochs=1)
        pipeline.run()
        dump_file = tmp_path / "forensics" / "rank0.json"
        assert dump_file.exists(), "watchdog never dumped during the stalled step"
        dump = json.loads(dump_file.read_text())
        assert dump["rank"] == 0
        assert "no span/step progress" in dump["reason"]
        assert any(t["stack"] for t in dump["threads"])

    def test_uncaught_exception_dumps_forensics(self, tmp_path, single_runtime):
        class BoomStage(_TeleStage):
            def post_epoch(self):
                raise RuntimeError("boom mid-run")

        pipeline = dml.TrainingPipeline(name="boom", telemetry={"dir": str(tmp_path / "tele")})
        pipeline.append_stage(BoomStage(_batches(n=4)), max_epochs=1)
        with pytest.raises(RuntimeError, match="boom"):
            pipeline.run()
        dump = json.loads((tmp_path / "forensics" / "rank0.json").read_text())
        assert "uncaught exception" in dump["reason"]
        assert "boom mid-run" in dump["reason"]
        assert not pipeline.telemetry_armed  # teardown still disarmed cleanly


# ---------------------------------------------------------------------------
# goodput advisor (ROADMAP-3 slice): doctored ledgers -> concrete knobs
# ---------------------------------------------------------------------------


class TestGoodputAdvisor:
    def _row(self, epoch, epoch_s, data_wait_s, pad_fraction=None, shard_reader=None):
        return {
            "epoch": epoch,
            "epoch_s": epoch_s,
            "data_wait_s": data_wait_s,
            "ckpt_s": 0.0,
            "stall_s": 0.1,
            "productive_s": max(epoch_s - data_wait_s - 0.1, 0.0),
            "goodput": None,
            "mfu": None,
            "pad_fraction": pad_fraction,
            "shard_reader": shard_reader,
        }

    def test_quiet_below_the_threshold(self):
        from dmlcloud_tpu.telemetry.goodput import advise_rows

        assert advise_rows([self._row(1, 10.0, 1.0), self._row(2, 10.0, 2.9)]) == []
        assert advise_rows([]) == []

    def test_data_wait_dominance_suggests_prefetch(self):
        from dmlcloud_tpu.telemetry.goodput import advise_rows

        advice = advise_rows([self._row(1, 10.0, 0.5), self._row(2, 10.0, 4.2)])
        assert len(advice) == 1
        assert "prefetch" in advice[0] and "host_prefetch" in advice[0]
        assert "epoch(s) 2" in advice[0]

    def test_pad_mask_adds_the_pack_stream_suggestion(self):
        from dmlcloud_tpu.telemetry.goodput import advise_rows

        advice = advise_rows([self._row(1, 10.0, 4.0, pad_fraction=0.72)])
        assert len(advice) == 2
        assert "pack_stream" in advice[1] and "72%" in advice[1]
        # a mask with little padding does not trigger the packing advice
        advice = advise_rows([self._row(1, 10.0, 4.0, pad_fraction=0.05)])
        assert len(advice) == 1

    def test_shard_reader_starvation_targets_the_reader_knobs(self):
        """When a disk ShardReader fed the starved epochs, the advice names
        the reader's own knobs — buffers= / read_ahead= — INSTEAD of the
        generic downstream prefetch row (which would only move the same
        starvation one stage later)."""
        from dmlcloud_tpu.telemetry.goodput import advise_rows

        advice = advise_rows([self._row(1, 10.0, 4.5, shard_reader=1.0)])
        assert len(advice) == 1
        assert "ShardReader" in advice[0]
        assert "buffers=" in advice[0] and "read_ahead=" in advice[0]
        assert "host_prefetch" not in advice[0]

    def test_shard_reader_in_healthy_epoch_keeps_generic_advice(self):
        """The reader advice keys off the STARVED epochs: a ShardReader that
        fed only well-overlapped epochs doesn't hijack the generic row."""
        from dmlcloud_tpu.telemetry.goodput import advise_rows

        rows = [
            self._row(1, 10.0, 0.2, shard_reader=1.0),  # healthy, reader-fed
            self._row(2, 10.0, 4.5),  # starved, generic iterable
        ]
        advice = advise_rows(rows)
        assert len(advice) == 1
        assert "host_prefetch" in advice[0]
        assert "ShardReader" not in advice[0]

    def test_shard_reader_advice_composes_with_pad_advice(self):
        from dmlcloud_tpu.telemetry.goodput import advise_rows

        advice = advise_rows([self._row(1, 10.0, 4.0, pad_fraction=0.4, shard_reader=1.0)])
        assert len(advice) == 2
        assert "read_ahead=" in advice[0]
        assert "pack_stream" in advice[1]

    def test_ledger_advise_delegates(self):
        from dmlcloud_tpu.telemetry.goodput import GoodputLedger, advise_rows

        rows = [self._row(1, 10.0, 5.0, pad_fraction=0.5)]
        assert GoodputLedger(rows).advise() == advise_rows(rows)

    def test_diag_run_reports_advice_from_doctored_ledger(self, tmp_path, capsys):
        """diag --run derives the SAME advice from the persisted
        goodput.json rows — no live tracker needed."""
        tele = tmp_path / "telemetry"
        tele.mkdir()
        doctored = {
            "v": 1,
            "epochs": [self._row(1, 10.0, 6.0, pad_fraction=0.7)],
            "totals": {"epochs": 1, "wall_s": 10.0, "compile_s": 0.0, "data_wait_s": 6.0,
                       "ckpt_s": 0.0, "host_stall_s": 0.1, "productive_s": 3.9,
                       "goodput_frac": 0.39, "mfu": None},
        }
        (tele / "goodput.json").write_text(json.dumps(doctored))
        rc = cli_main(["diag", "--json", "--run", str(tmp_path)])
        info = json.loads(capsys.readouterr().out)
        assert rc == 0
        advice = info["telemetry"]["advice"]
        assert len(advice) == 2
        assert "prefetch" in advice[0] and "pack_stream" in advice[1]

        cli_main(["diag", "--run", str(tmp_path)])
        out = capsys.readouterr().out
        assert "advice:" in out and "pack_stream" in out

    def test_healthy_run_gets_no_advice(self, tele_run, capsys):
        """The real telemetry e2e run (tiny batches, no starvation) stays
        quiet — the advisor only speaks on evidence."""
        from dmlcloud_tpu.telemetry.goodput import ledger_from_tracker

        ledger = ledger_from_tracker(tele_run.tracker)
        for line in ledger.advise():
            assert "data_wait" in line  # if it ever fires here, it is honest
