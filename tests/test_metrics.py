"""Metric engine parity suite, modeled on the reference's
test/test_metrics.py: local-vs-global equivalence at world 1, partial-dim
shapes, serialization round-trip, empty -> None, tracker epoch bookkeeping,
double-track errors, prefix reduction, state_dict."""

import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.metrics import (
    MetricReducer,
    MetricTracker,
    Reduction,
    _pack_scalar_metrics,
    _unpack_scalar_metrics,
    reduce_tensor,
)


class TestFusedScalarExchange:
    """The packed single-collective epoch exchange: pack on N simulated ranks,
    stack (what all_gather_array returns), unpack — must reproduce the
    per-metric reductions and the ragged-tracking diagnostics."""

    NAMES = ["acc", "count", "loss", "lr"]
    REDUCTIONS = {
        "acc": Reduction.MAX,
        "count": Reduction.SUM,
        "loss": Reduction.MEAN,
        "lr": Reduction.MIN,
    }

    def _gather(self, per_rank_locals):
        return np.stack([_pack_scalar_metrics(self.NAMES, loc) for loc in per_rank_locals])

    def test_reductions_across_ranks(self):
        locals_ = [
            {"acc": (False, 0.5), "count": (False, 10), "loss": (False, 2.0), "lr": (False, 0.1)},
            {"acc": (False, 0.7), "count": (False, 12), "loss": (False, 4.0), "lr": (False, 0.3)},
        ]
        out = _unpack_scalar_metrics(self.NAMES, self._gather(locals_), self.REDUCTIONS)
        assert out["acc"] == pytest.approx(0.7)
        assert out["count"] == pytest.approx(22)
        assert out["loss"] == pytest.approx(3.0)
        assert out["lr"] == pytest.approx(0.1)

    def test_all_empty_gives_none(self):
        locals_ = [{n: (True, None) for n in self.NAMES} for _ in range(3)]
        out = _unpack_scalar_metrics(self.NAMES, self._gather(locals_), self.REDUCTIONS)
        assert all(v is None for v in out.values())

    def test_ragged_tracking_raises(self):
        locals_ = [
            {"acc": (False, 0.5), "count": (False, 1), "loss": (False, 2.0), "lr": (False, 0.1)},
            {"acc": (True, None), "count": (False, 1), "loss": (False, 2.0), "lr": (False, 0.1)},
        ]
        with pytest.raises(ValueError, match="some workers tracked"):
            _unpack_scalar_metrics(self.NAMES, self._gather(locals_), self.REDUCTIONS)

    def test_diverged_name_sets_detected(self):
        a = _pack_scalar_metrics(["loss", "x"], {"loss": (False, 1.0), "x": (False, 2.0)})
        b = _pack_scalar_metrics(["loss", "y"], {"loss": (False, 1.0), "y": (False, 2.0)})
        with pytest.raises(ValueError, match="disagree"):
            _unpack_scalar_metrics(["loss", "x"], np.stack([a, b]), {"loss": Reduction.MEAN, "x": Reduction.MEAN})

    def test_int_sum_exact(self):
        """SUM counters transit as float32 — exact for realistic per-epoch
        batch counts (< 2**24)."""
        locals_ = [{"count": (False, 2**20 + i)} for i in range(4)]
        gathered = np.stack([_pack_scalar_metrics(["count"], loc) for loc in locals_])
        out = _unpack_scalar_metrics(["count"], gathered, {"count": Reduction.SUM})
        assert int(out["count"]) == sum(2**20 + i for i in range(4))

    def test_int_sum_exact_past_2_24_combined(self):
        """Per-rank values that are f32-exact must combine exactly even when
        the cross-rank TOTAL exceeds 2**24 (the combine runs in float64)."""
        locals_ = [{"count": (False, 2**23)}, {"count": (False, 2**23 + 1)}]
        gathered = np.stack([_pack_scalar_metrics(["count"], loc) for loc in locals_])
        out = _unpack_scalar_metrics(["count"], gathered, {"count": Reduction.SUM})
        assert int(out["count"]) == 2**24 + 1  # not representable in f32

    def test_inexact_sum_counter_warns_loudly(self, caplog):
        """An integer SUM counter past 2**24 gets a once-per-metric warning
        naming the exact fix (ADVICE/VERDICT r3: the caveat must be loud)."""
        import logging

        from dmlcloud_tpu import metrics as metrics_mod

        metrics_mod._INEXACT_SUM_WARNED.discard("big")
        reds = {"big": Reduction.SUM, "loss": Reduction.MEAN}
        local = {"big": (False, 2**24 + 1), "loss": (False, 2**24 + 1.0)}
        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu.metrics"):
            _pack_scalar_metrics(["big", "loss"], local, reds)
        warnings = [r for r in caplog.records if "exact" in r.getMessage()]
        assert len(warnings) == 1  # SUM counter warns; MEAN float does not
        assert "big" in warnings[0].getMessage()
        assert "dim=()" in warnings[0].getMessage()
        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu.metrics"):
            _pack_scalar_metrics(["big", "loss"], local, reds)
        assert not [r for r in caplog.records if "exact" in r.getMessage()]  # once per metric


class TestReduceTensor:
    def test_mean_all_dims(self):
        t = np.arange(12.0).reshape(3, 4)
        assert reduce_tensor(t, Reduction.MEAN) == pytest.approx(5.5)

    def test_partial_dims(self):
        t = np.arange(24.0).reshape(2, 3, 4)
        out = reduce_tensor(t, Reduction.SUM, dim=[0, 2])
        assert out.shape == (3,)
        np.testing.assert_array_equal(out, t.sum(axis=(0, 2)))

    def test_min_max(self):
        t = np.array([3.0, -1.0, 7.0])
        assert reduce_tensor(t, Reduction.MIN) == -1.0
        assert reduce_tensor(t, Reduction.MAX) == 7.0


class TestMetricReducer:
    def test_local_global_equal_world1(self, single_runtime):
        r = MetricReducer(Reduction.MEAN)
        for v in (1.0, 2.0, 3.0):
            r.append(v)
        np.testing.assert_allclose(r.reduce_locally(), 2.0)
        np.testing.assert_allclose(r.reduce_globally(), 2.0)

    def test_jax_values_accepted(self, single_runtime):
        r = MetricReducer(Reduction.SUM)
        r.append(jnp.float32(1.5))
        r.append(jnp.float32(2.5))
        assert float(r.reduce_globally()) == 4.0

    def test_dim_reduction_shapes(self, single_runtime):
        r = MetricReducer(Reduction.MEAN, dim=0)
        r.append(np.ones((5, 3)))
        r.append(np.zeros((5, 3)))
        out = r.reduce_locally()
        assert out.shape == (3,)
        np.testing.assert_allclose(out, 0.5)

    def test_empty_returns_none(self, single_runtime):
        r = MetricReducer(Reduction.MEAN)
        assert r.reduce_locally() is None
        assert r.reduce_globally() is None

    def test_state_dict_roundtrip(self, single_runtime):
        r = MetricReducer(Reduction.MAX, dim=[1])
        r.append(np.arange(6.0).reshape(2, 3))
        state = r.state_dict()
        r2 = MetricReducer()
        r2.load_state_dict(state)
        assert r2.reduction == Reduction.MAX
        assert r2.dim == [1]
        np.testing.assert_array_equal(r2.reduce_locally(), r.reduce_locally())

    def test_list_protocol(self):
        r = MetricReducer()
        r += 1.0
        r.extend([2.0, 3.0])
        assert len(r) == 3
        del r[0]
        assert len(r) == 2
        r[0] = 9.0
        assert r[0] == 9.0
        r.clear()
        assert len(r) == 0

    def test_invalid_reduction(self):
        with pytest.raises(ValueError):
            MetricReducer("bogus")


class TestMetricTracker:
    def test_register_and_track(self, single_runtime):
        t = MetricTracker()
        t.register_metric("loss", Reduction.MEAN)
        t.track("loss", 2.0)
        t.track("loss", 4.0)
        t.next_epoch()
        assert t.epoch == 2
        assert t["loss"] == [pytest.approx(3.0)]

    def test_unknown_metric_raises(self):
        t = MetricTracker()
        with pytest.raises(ValueError):
            t.track("nope", 1.0)
        with pytest.raises(ValueError):
            t["nope"]

    def test_double_register_raises(self):
        t = MetricTracker()
        t.register_metric("m")
        with pytest.raises(ValueError):
            t.register_metric("m")

    def test_dim_without_reduction_raises(self):
        t = MetricTracker()
        with pytest.raises(ValueError):
            t.register_metric("m", dim=[0])

    def test_manual_metric_once_per_epoch(self, single_runtime):
        t = MetricTracker()
        t.register_metric("lr")
        t.track("lr", 0.1)
        with pytest.raises(ValueError):
            t.track("lr", 0.2)
        t.next_epoch()
        t.track("lr", 0.2)
        assert t["lr"] == [0.1]

    def test_late_registration_pads_history(self, single_runtime):
        t = MetricTracker()
        t.register_metric("a", Reduction.MEAN)
        t.track("a", 1.0)
        t.next_epoch()
        t.register_metric("b", Reduction.MEAN)
        t.track("b", 5.0)
        t.next_epoch()
        assert t["b"] == [None, pytest.approx(5.0)]

    def test_untracked_reduced_metric_appends_none(self, single_runtime):
        t = MetricTracker()
        t.register_metric("loss", Reduction.MEAN)
        t.next_epoch()
        assert t["loss"] == [None]

    def test_prefix_reduction(self, single_runtime):
        t = MetricTracker()
        t.register_metric("train/loss", Reduction.MEAN)
        t.register_metric("val/loss", Reduction.MEAN)
        t.track("train/loss", 1.0)
        t.track("val/loss", 2.0)
        t.reduce_all(prefix="train/")
        assert t.has_value("train/loss")
        assert not t.has_value("val/loss")
        # strict double-reduce raises
        with pytest.raises(ValueError):
            t.reduce_all(prefix="train/")
        t.next_epoch()
        assert t["train/loss"] == [pytest.approx(1.0)]
        assert t["val/loss"] == [pytest.approx(2.0)]

    def test_current_value_and_is_reduced(self, single_runtime):
        t = MetricTracker()
        t.register_metric("loss", Reduction.MEAN)
        t.register_metric("note")
        assert t.is_reduced_metric("loss")
        assert not t.is_reduced_metric("note")
        t.track("loss", 1.0)
        assert t.current_value("loss") is None
        t.reduce_all()
        assert t.current_value("loss") == pytest.approx(1.0)

    def test_state_dict_roundtrip(self, single_runtime):
        t = MetricTracker()
        t.register_metric("loss", Reduction.MEAN)
        t.track("loss", 1.0)
        t.next_epoch()
        t.track("loss", 3.0)
        state = t.state_dict()

        t2 = MetricTracker()
        t2.load_state_dict(state)
        assert t2.epoch == 2
        t2.next_epoch()
        assert t2["loss"][0] == pytest.approx(1.0)
        assert t2["loss"][1] == pytest.approx(3.0)

    def test_str(self):
        t = MetricTracker()
        t.register_metric("x")
        assert "x" in str(t)


class TestPerTrackerInexactWarning:
    """The inexact-SUM warning dedupe is per-tracker (a second pipeline or
    test in the same process warns again), and the exactness check runs as
    one vectorized pass over the already-packed vector."""

    def test_warned_set_scopes_the_dedupe(self, caplog):
        import logging

        from dmlcloud_tpu.metrics import _pack_scalar_metrics

        reds = {"big": Reduction.SUM}
        local = {"big": (False, 2**24 + 1)}
        first_tracker, second_tracker = set(), set()
        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu.metrics"):
            _pack_scalar_metrics(["big"], local, reds, warned=first_tracker)
            _pack_scalar_metrics(["big"], local, reds, warned=first_tracker)  # deduped
            _pack_scalar_metrics(["big"], local, reds, warned=second_tracker)  # warns again
        msgs = [r for r in caplog.records if "exact" in r.getMessage()]
        assert len(msgs) == 2
        assert first_tracker == {"big"} and second_tracker == {"big"}

    def test_each_tracker_owns_its_set(self):
        t1, t2 = MetricTracker(), MetricTracker()
        t1._inexact_sum_warned.add("big")
        assert "big" not in t2._inexact_sum_warned

    def test_packed_values_unchanged_by_hoisted_conversion(self):
        """The one-pass conversion must produce the identical f32 payload
        the per-element np.float32() casts did."""
        from dmlcloud_tpu.metrics import _pack_scalar_metrics

        names = ["a", "b", "c"]
        local = {"a": (False, 1.5), "b": (True, None), "c": (False, 2**24 + 1)}
        vec = _pack_scalar_metrics(names, local, warned=set())
        n = len(names)
        assert vec.dtype == np.float32
        assert list(vec[1 : 1 + n]) == [0.0, 1.0, 0.0]
        assert vec[1 + n] == np.float32(1.5)
        assert vec[1 + n + 1] == np.float32(0.0)  # empty slot stays zero
        assert vec[1 + n + 2] == np.float32(2**24 + 1)
