"""Test fixtures: a virtual 8-device CPU mesh in one process.

The reference fakes a cluster with a world-size-1 HashStore process group
(/root/reference/test/conftest.py:6-10). The TPU build goes further: XLA's
host-platform device count gives *real* multi-device pjit/psum execution on
CPU (SURVEY.md §4 testing blueprint) — sharding bugs show up for real.

Must run before any test imports trigger backend initialisation.
"""

import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")
# Serial dispatch: concurrent collective programs starve XLA:CPU's rendezvous
# on few-core CI machines (see pipeline._init_mesh).
jax.config.update("jax_cpu_enable_async_dispatch", False)

import pytest  # noqa: E402

# NOTE: do NOT arm the persistent XLA compilation cache (compile/cache.py)
# globally here, tempting as it is for the engine-heavy serve tests: on
# this jax/XLA:CPU, cache-deserialized executables destabilize the live
# 8-device collective programs later in the suite (segfault in
# test_resume's pipeline run — same failure family as the known
# jax.clear_caches() hazard, see CHANGES.md PR 3).

from dmlcloud_tpu.parallel import runtime  # noqa: E402


@pytest.fixture
def single_runtime():
    """Single-process runtime (the reference's dummy process group analog)."""
    runtime.init_single()
    yield
    runtime.deinitialize()


@pytest.fixture
def mesh8():
    """An 8-device data-parallel mesh on the forced CPU devices."""
    from dmlcloud_tpu.parallel import mesh as mesh_lib

    assert len(jax.devices()) == 8, "conftest must run before backend init"
    return mesh_lib.create_mesh({"data": -1})


# ---------------------------------------------------------------------------
# Session-scoped model fixtures (ROADMAP item 5c: tier-1 wall-time budget).
#
# test_serve, test_serve_router, test_speculative and test_quant each used
# to init their own per-module copy of the same tiny LMs; building each
# exactly ONCE per session removes the redundant inits and the re-traced
# init programs from the suite's wall clock. All consumers treat params as
# immutable (engines copy into pools, LoRA builds new trees), so sharing
# one instance across files is safe.
# ---------------------------------------------------------------------------


def _init_lm(cfg_kw, seed, init_len=4):
    import jax.numpy as jnp

    from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig

    cfg = TransformerConfig(dtype=jnp.float32, **cfg_kw)
    model = DecoderLM(cfg)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.ones((1, init_len), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="session")
def tiny_model():
    """The 61-vocab fp32 serve model shared by test_serve/test_serve_router:
    exact arithmetic so token-identity assertions are bitwise-ish."""
    return _init_lm(
        dict(vocab_size=61, num_layers=2, num_heads=4, num_kv_heads=2,
             head_dim=8, hidden_dim=32, mlp_dim=64, max_seq_len=64),
        seed=0,
    )


@pytest.fixture(scope="session")
def spec_models():
    """Target (2-layer) + independent random draft (1-layer) pair for the
    speculative-decoding exactness suite (test_speculative)."""
    import jax.numpy as jnp
    import numpy as np

    from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig

    def lm(layers, seed):
        cfg = TransformerConfig(
            vocab_size=48, num_layers=layers, num_heads=2, num_kv_heads=1,
            head_dim=8, hidden_dim=16, mlp_dim=32, max_seq_len=96,
            dtype=jnp.float32,
        )
        model = DecoderLM(cfg)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 48, (1, 8)), jnp.int32
        )
        return model, model.init(jax.random.PRNGKey(seed), tokens)["params"]

    target, tparams = lm(2, 0)
    draft, dparams = lm(1, 7)
    return target, tparams, draft, dparams


@pytest.fixture(scope="session")
def quant_lm():
    """64-vocab LM for the weight-only int8 decode tests (test_quant)."""
    import jax.numpy as jnp
    import numpy as np

    from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig

    cfg = TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=2, num_kv_heads=1, head_dim=8,
        hidden_dim=16, mlp_dim=32, max_seq_len=48, dtype=jnp.float32,
    )
    model = DecoderLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (1, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params
