"""Test fixtures: a virtual 8-device CPU mesh in one process.

The reference fakes a cluster with a world-size-1 HashStore process group
(/root/reference/test/conftest.py:6-10). The TPU build goes further: XLA's
host-platform device count gives *real* multi-device pjit/psum execution on
CPU (SURVEY.md §4 testing blueprint) — sharding bugs show up for real.

Must run before any test imports trigger backend initialisation.
"""

import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")
# Serial dispatch: concurrent collective programs starve XLA:CPU's rendezvous
# on few-core CI machines (see pipeline._init_mesh).
jax.config.update("jax_cpu_enable_async_dispatch", False)

import pytest  # noqa: E402

from dmlcloud_tpu.parallel import runtime  # noqa: E402


@pytest.fixture
def single_runtime():
    """Single-process runtime (the reference's dummy process group analog)."""
    runtime.init_single()
    yield
    runtime.deinitialize()


@pytest.fixture
def mesh8():
    """An 8-device data-parallel mesh on the forced CPU devices."""
    from dmlcloud_tpu.parallel import mesh as mesh_lib

    assert len(jax.devices()) == 8, "conftest must run before backend init"
    return mesh_lib.create_mesh({"data": -1})
