"""Attention ops: flash (Pallas, interpret mode on CPU) and ring attention
(real 8-device shard_map + ppermute) against the reference einsum path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.models.transformer import _dot_attention
from dmlcloud_tpu.ops.flash_attention import flash_attention
from dmlcloud_tpu.ops.ring_attention import ring_attention_sharded
from dmlcloud_tpu.parallel import mesh as mesh_lib

pytestmark = pytest.mark.slow


def _qkv(b=2, t=128, h=4, kh=None, d=32, seed=0, dtype=jnp.float32):
    kh = kh or h
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, t, h, d), dtype) * 0.5
    k = jnp.asarray(rng.randn(b, t, kh, d), dtype) * 0.5
    v = jnp.asarray(rng.randn(b, t, kh, d), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(t=128)
        expected = _dot_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_gqa(self):
        q, k, v = _qkv(h=8, kh=2)
        expected = _dot_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_dead_rows_write_exact_zero(self):
        """A row fully masked inside VISITED blocks (possible only through
        the internal shifted-window path the ring's behind-hops use) must
        write out == 0 and an effectively -inf lse — not a mean of V."""
        from dmlcloud_tpu.ops.flash_attention import _flash_lse

        q, k, v = _qkv(b=1, t=64, h=1, d=16)
        # internal call: causal=False, window=0 keeps only k_pos > q_pos,
        # so the LAST row attends to nothing while its K blocks are visited
        out, lse = _flash_lse(q, k, v, None, False, 1.0, 32, 32, True, 0)
        out = np.asarray(out)
        lse = np.asarray(lse).reshape(1, 1, 64)  # raw [B*H, T]
        assert np.all(out[0, -1, 0] == 0.0)
        assert lse[0, 0, -1] < -1e29
        # live rows match a reference softmax over their keys (k > q)
        s = np.einsum("td,sd->ts", np.asarray(q)[0, :, 0], np.asarray(k)[0, :, 0])
        mask = np.arange(64)[None, :] > np.arange(64)[:, None]
        s = np.where(mask, s, -np.inf)
        p = np.exp(s[:-1] - s[:-1].max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        expected = p @ np.asarray(v)[0, :, 0]
        np.testing.assert_allclose(out[0, :-1, 0], expected, atol=2e-5, rtol=2e-5)

    def test_block_divisibility_enforced(self):
        q, k, v = _qkv(t=100)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, block_q=64, block_k=64)

    @pytest.mark.parametrize("t", [384, 192])
    def test_default_blocks_auto_shrink(self, t):
        """Seq lens that are multiples of 128/64 but not of the default 256
        block must auto-select the largest dividing block, not raise."""
        q, k, v = _qkv(t=t, h=2, d=16)
        expected = _dot_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True)  # default block sizes
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_causal_cross_length_rejected(self):
        """Causal with T != S would silently use the wrong mask alignment —
        must raise, not return top-left-masked garbage."""
        q, _, _ = _qkv(t=64, h=2, d=16)
        _, k, v = _qkv(t=128, h=2, d=16, seed=1)
        with pytest.raises(ValueError, match="equal Q/KV sequence lengths"):
            flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        # non-causal cross-length is fine
        out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32)
        expected = _dot_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_grad_flows(self):
        q, k, v = _qkv(t=64, h=2, d=16)

        def loss(q):
            return jnp.sum(flash_attention(q, k, v, causal=True, block_q=32, block_k=32) ** 2)

        g = jax.grad(loss)(q)
        assert g.shape == q.shape
        assert bool(jnp.all(jnp.isfinite(g)))

    @pytest.mark.parametrize("causal", [True, False])
    def test_backward_matches_reference(self, causal):
        """The Pallas backward kernels (dQ; dK/dV) against autodiff through
        the reference einsum path — multi-block grids in both directions."""
        from dmlcloud_tpu.ops.flash_attention import _reference_attention

        q, k, v = _qkv(t=128, h=4, d=32)
        cot = jnp.asarray(np.random.RandomState(7).randn(*q.shape), q.dtype)

        def flash_loss(q, k, v):
            return jnp.vdot(flash_attention(q, k, v, causal=causal, block_q=32, block_k=64), cot)

        def ref_loss(q, k, v):
            return jnp.vdot(_reference_attention(q, k, v, causal, 1.0 / np.sqrt(q.shape[-1])), cot)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
            )

    def test_backward_gqa_matches_reference(self):
        """GQA backward: grouped heads must accumulate into shared dK/dV."""
        from dmlcloud_tpu.ops.flash_attention import _reference_attention

        q, k, v = _qkv(t=64, h=8, kh=2, d=16)
        cot = jnp.asarray(np.random.RandomState(8).randn(*q.shape), q.dtype)

        def flash_loss(q, k, v):
            return jnp.vdot(flash_attention(q, k, v, causal=True, block_q=32, block_k=32), cot)

        def ref_loss(q, k, v):
            return jnp.vdot(_reference_attention(q, k, v, True, 1.0 / np.sqrt(q.shape[-1])), cot)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
            )

    @pytest.mark.parametrize("window", [1, 17, 32, 100, 128])
    def test_sliding_window_matches_reference(self, window):
        """Window values spanning sub-block, block-multiple, and full-seq —
        exercises the stale-block skip and both mask boundaries."""
        from dmlcloud_tpu.ops.flash_attention import _reference_attention

        q, k, v = _qkv(t=128, h=2, d=16, seed=5)
        expected = _reference_attention(q, k, v, True, 1.0 / np.sqrt(16), window=window)
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("window", [24, 64])
    def test_sliding_window_backward_matches_reference(self, window):
        """Windowed backward in both kernels (dq stale-block skip; dkv
        past-window skip), with uneven blocks."""
        from dmlcloud_tpu.ops.flash_attention import _reference_attention

        q, k, v = _qkv(t=128, h=4, kh=2, d=16, seed=6)
        cot = jnp.asarray(np.random.RandomState(9).randn(*q.shape), q.dtype)

        def flash_loss(q, k, v):
            return jnp.vdot(
                flash_attention(q, k, v, causal=True, block_q=64, block_k=32, window=window), cot
            )

        def ref_loss(q, k, v):
            return jnp.vdot(_reference_attention(q, k, v, True, 1.0 / np.sqrt(16), window=window), cot)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
            )

    def test_sliding_window_requires_causal(self):
        q, k, v = _qkv(t=64, h=2, d=16)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=16)
        with pytest.raises(ValueError, match=">= 1"):
            flash_attention(q, k, v, causal=True, window=0)

    def test_backward_uneven_qk_blocks(self):
        """block_q != block_k exercises the diagonal-skip bounds in both
        backward kernels (dq upper bound, dkv lower bound)."""
        from dmlcloud_tpu.ops.flash_attention import _reference_attention

        q, k, v = _qkv(t=128, h=2, d=16, seed=3)

        def flash_loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True, block_q=64, block_k=16) ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(_reference_attention(q, k, v, True, 1.0 / np.sqrt(q.shape[-1])) ** 2)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
            )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        """seq sharded 8 ways; ring result == unsharded reference."""
        mesh = mesh_lib.create_mesh({"seq": 8})
        q, k, v = _qkv(b=1, t=64, h=2, d=16)
        expected = _dot_attention(q, k, v, causal=causal)
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_gqa_ring(self):
        mesh = mesh_lib.create_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=1, t=32, h=4, kh=2, d=16)
        expected = _dot_attention(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_data_and_seq_axes(self):
        mesh = mesh_lib.create_mesh({"data": 2, "seq": 4})
        q, k, v = _qkv(b=2, t=32, h=2, d=16)
        expected = _dot_attention(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_grad_flows(self):
        mesh = mesh_lib.create_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=1, t=32, h=2, d=16)

        def loss(q, k, v):
            return jnp.sum(ring_attention_sharded(q, k, v, mesh) ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g, ref_arr in zip(grads, (q, k, v)):
            assert g.shape == ref_arr.shape
            assert bool(jnp.all(jnp.isfinite(g)))

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_reference_on_mesh(self, causal):
        """Ring grads == unsharded einsum grads on the 8-device mesh. This
        also validates the lse-cotangent path of the flash backward: the
        blockwise merge differentiates through each block's logsumexp."""
        mesh = mesh_lib.create_mesh({"seq": 8})
        q, k, v = _qkv(b=1, t=64, h=2, d=16, seed=5)
        cot = jnp.asarray(np.random.RandomState(9).randn(*q.shape), q.dtype)

        def ring_loss(q, k, v):
            return jnp.vdot(ring_attention_sharded(q, k, v, mesh, causal=causal), cot)

        def ref_loss(q, k, v):
            return jnp.vdot(_dot_attention(q, k, v, causal=causal), cot)

        got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=1e-4, rtol=1e-4, err_msg=f"d{name}"
            )


class TestWindowedRing:
    """Sliding-window ring attention: global-position window over the sharded
    sequence, truncated ring rotation."""

    @pytest.mark.parametrize("window", [1, 5, 8, 13, 40, 64])
    def test_matches_windowed_reference(self, window):
        """Windows smaller than, equal to, and spanning multiple local
        blocks (Tl=8 at 8 devices), incl. full-seq."""
        from dmlcloud_tpu.ops.flash_attention import _reference_attention

        mesh = mesh_lib.create_mesh({"seq": 8})
        q, k, v = _qkv(b=1, t=64, h=2, d=16, seed=11)
        expected = _reference_attention(q, k, v, True, 1.0 / np.sqrt(16), window=window)
        out = ring_attention_sharded(q, k, v, mesh, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("window", [5, 13])
    def test_grads_match_windowed_reference(self, window):
        from dmlcloud_tpu.ops.flash_attention import _reference_attention

        mesh = mesh_lib.create_mesh({"seq": 8})
        q, k, v = _qkv(b=1, t=64, h=2, d=16, seed=12)
        cot = jnp.asarray(np.random.RandomState(13).randn(*q.shape), q.dtype)

        def ring_loss(q, k, v):
            return jnp.vdot(ring_attention_sharded(q, k, v, mesh, causal=True, window=window), cot)

        def ref_loss(q, k, v):
            return jnp.vdot(_reference_attention(q, k, v, True, 1.0 / np.sqrt(16), window=window), cot)

        got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=1e-4, rtol=1e-4, err_msg=f"d{name}"
            )

    def test_gqa_windowed_ring(self):
        from dmlcloud_tpu.ops.flash_attention import _reference_attention

        mesh = mesh_lib.create_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=1, t=32, h=4, kh=2, d=16, seed=14)
        expected = _reference_attention(q, k, v, True, 1.0 / np.sqrt(16), window=11)
        out = ring_attention_sharded(q, k, v, mesh, causal=True, window=11)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_window_requires_causal(self):
        mesh = mesh_lib.create_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=1, t=32, h=2, d=16)
        with pytest.raises(ValueError, match="causal"):
            ring_attention_sharded(q, k, v, mesh, causal=False, window=8)


class TestFlashLse:
    def test_lse_value(self):
        """return_lse must equal the actual logsumexp of scaled scores."""
        q, k, v = _qkv(b=1, t=64, h=2, d=16)
        out, lse = flash_attention(q, k, v, causal=False, block_q=32, block_k=32, return_lse=True)
        scale = 1.0 / np.sqrt(16)
        scores = jnp.einsum("bthd,bshd->bhts", q, k) * scale
        expected = jax.scipy.special.logsumexp(scores.astype(jnp.float32), axis=-1)  # [B,H,T]
        np.testing.assert_allclose(
            np.asarray(lse), np.asarray(expected.transpose(0, 2, 1)), atol=2e-5, rtol=2e-5
        )

    def test_lse_grad(self):
        """Gradients THROUGH the lse output alone (d lse/d s = softmax) —
        the delta-shift in the backward kernels."""
        q, k, v = _qkv(b=1, t=32, h=2, d=16)
        glse = jnp.asarray(np.random.RandomState(3).randn(1, 32, 2), jnp.float32)
        scale = 1.0 / np.sqrt(16)

        def flash_loss(q, k, v):
            _, lse = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, return_lse=True)
            return jnp.vdot(lse, glse)

        def ref_loss(q, k, v):
            scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
            mask = jnp.tril(jnp.ones((32, 32), bool))
            scores = jnp.where(mask[None, None], scores, -1e30)
            lse = jax.scipy.special.logsumexp(scores, axis=-1).transpose(0, 2, 1)
            return jnp.vdot(lse, glse)

        got = jax.grad(flash_loss, argnums=(0, 1))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1))(q, k, v)
        for g, w, name in zip(got, want, "qk"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
            )


class TestFlashSegments:
    """Packed-sequence (segment_ids) masking in the flash kernels."""

    @staticmethod
    def _segs(b, t, seed):
        rng = np.random.RandomState(seed)
        segs = np.zeros((b, t), np.int32)
        for r in range(b):
            pos, sid = 0, 1
            while pos < t:
                ln = int(rng.randint(8, 40))
                segs[r, pos : pos + ln] = sid
                pos += ln
                sid += 1
        return jnp.asarray(segs)

    @staticmethod
    def _ref(q, k, v, segs, causal, window=None):
        from dmlcloud_tpu.ops.flash_attention import _NEG_INF

        b, t, h, d = q.shape
        kh = k.shape[2]
        group = h // kh
        qg = q.reshape(b, t, kh, group, d)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) / np.sqrt(d)
        mask = segs[:, :, None] == segs[:, None, :]
        if causal:
            mask = mask & jnp.tril(jnp.ones((t, t), bool))[None]
        if window is not None:
            pos = jnp.arange(t)
            mask = mask & ((pos[:, None] - pos[None, :]) < window)[None]
        scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        return jnp.einsum("bkgts,bskd->btkgd", probs, v).reshape(b, t, h, d)

    @pytest.mark.parametrize("causal", [True, False])
    def test_fwd_matches_reference(self, causal):
        q, k, v = _qkv(b=2, t=128, h=2, d=16, seed=21)
        segs = self._segs(2, 128, 5)
        want = self._ref(q, k, v, segs, causal)
        got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32, segment_ids=segs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_fwd_gqa_with_window(self):
        q, k, v = _qkv(b=1, t=128, h=4, kh=2, d=16, seed=22)
        segs = self._segs(1, 128, 6)
        want = self._ref(q, k, v, segs, True, window=23)
        got = flash_attention(
            q, k, v, causal=True, block_q=32, block_k=64, window=23, segment_ids=segs
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)

    def test_backward_matches_reference(self):
        q, k, v = _qkv(b=1, t=128, h=2, d=16, seed=23)
        segs = self._segs(1, 128, 7)
        cot = jnp.asarray(np.random.RandomState(24).randn(*q.shape), q.dtype)

        def flash_loss(q, k, v):
            return jnp.vdot(
                flash_attention(q, k, v, causal=True, block_q=64, block_k=32, segment_ids=segs), cot
            )

        def ref_loss(q, k, v):
            return jnp.vdot(self._ref(q, k, v, segs, True), cot)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
            )

    def test_shape_validation(self):
        q, k, v = _qkv(t=64, h=2, d=16)
        with pytest.raises(ValueError, match="segment_ids must be"):
            flash_attention(q, k, v, segment_ids=jnp.ones((2, 32), jnp.int32))
