"""Attention ops: flash (Pallas, interpret mode on CPU) and ring attention
(real 8-device shard_map + ppermute) against the reference einsum path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.models.transformer import _dot_attention
from dmlcloud_tpu.ops.flash_attention import flash_attention
from dmlcloud_tpu.ops.ring_attention import ring_attention_sharded
from dmlcloud_tpu.parallel import mesh as mesh_lib


def _qkv(b=2, t=128, h=4, kh=None, d=32, seed=0, dtype=jnp.float32):
    kh = kh or h
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, t, h, d), dtype) * 0.5
    k = jnp.asarray(rng.randn(b, t, kh, d), dtype) * 0.5
    v = jnp.asarray(rng.randn(b, t, kh, d), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(t=128)
        expected = _dot_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_gqa(self):
        q, k, v = _qkv(h=8, kh=2)
        expected = _dot_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_block_divisibility_enforced(self):
        q, k, v = _qkv(t=100)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, block_q=64, block_k=64)

    def test_grad_flows(self):
        q, k, v = _qkv(t=64, h=2, d=16)

        def loss(q):
            return jnp.sum(flash_attention(q, k, v, causal=True, block_q=32, block_k=32) ** 2)

        g = jax.grad(loss)(q)
        assert g.shape == q.shape
        assert bool(jnp.all(jnp.isfinite(g)))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        """seq sharded 8 ways; ring result == unsharded reference."""
        mesh = mesh_lib.create_mesh({"seq": 8})
        q, k, v = _qkv(b=1, t=64, h=2, d=16)
        expected = _dot_attention(q, k, v, causal=causal)
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_gqa_ring(self):
        mesh = mesh_lib.create_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=1, t=32, h=4, kh=2, d=16)
        expected = _dot_attention(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_data_and_seq_axes(self):
        mesh = mesh_lib.create_mesh({"data": 2, "seq": 4})
        q, k, v = _qkv(b=2, t=32, h=2, d=16)
        expected = _dot_attention(q, k, v, causal=True)
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)

    def test_grad_flows(self):
        mesh = mesh_lib.create_mesh({"seq": 4}, devices=jax.devices()[:4])
        q, k, v = _qkv(b=1, t=32, h=2, d=16)

        def loss(q, k, v):
            return jnp.sum(ring_attention_sharded(q, k, v, mesh) ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g, ref_arr in zip(grads, (q, k, v)):
            assert g.shape == ref_arr.shape
            assert bool(jnp.all(jnp.isfinite(g)))
