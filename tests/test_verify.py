"""IR-level program verifier (doc/lint.md DML6xx): the CPU tracer, the
rules over jaxpr + compiled artifact, the fixture corpus with EXACT
counts (including the dropped-donation case the AST pass provably passes
clean), the ``verify`` CLI, ``lint --ir`` integration with warm-cache
byte identity, the centralized :meth:`ServeEngine.signature_budget`
formula, and the runtime arms (``TrainingPipeline(verify=...)`` /
``ServeEngine(verify=...)``).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import dmlcloud_tpu as dml
from dmlcloud_tpu.lint import LintError
from dmlcloud_tpu.lint.engine import expand_rule_ids, lint_paths
from dmlcloud_tpu.lint.ir import (
    ProgramSpec, run_ir_rules, trace_program, verify_file, verify_main,
    verify_programs,
)
from dmlcloud_tpu.serve import ServeEngine

FIXTURES = os.path.join(os.path.dirname(__file__), "verify_fixtures")


def _fx(name):
    return os.path.join(FIXTURES, name)


def _rules(findings):
    return sorted(f.rule for f in findings)


# ------------------------------------------------- signature budget formula


class TestSignatureBudget:
    """Satellite lock: ONE formula, equal to the historical inline math."""

    @pytest.mark.parametrize("n_bb,n_tb", [(1, 1), (2, 3), (3, 4), (5, 2)])
    def test_matches_historical_inline_math(self, n_bb, n_tb):
        # plain: decode grid + prefill per table bucket
        b = ServeEngine.signature_budget(n_bb, n_tb)
        assert b["step"] == n_bb * n_tb + n_tb
        assert b["total"] == n_bb * n_tb + n_tb
        # spec: doubled prefill, fallback decode, draft+verify per round
        b = ServeEngine.signature_budget(n_bb, n_tb, spec=True)
        assert b["step"] == 2 * n_tb + n_bb * n_tb
        assert b["spec"] == n_bb * n_tb
        assert b["total"] == (2 * n_tb + n_bb * n_tb) + 2 * (n_bb * n_tb)
        # medusa: target-only prefill, fallback decode, one fused round sig
        b = ServeEngine.signature_budget(n_bb, n_tb, medusa=True)
        assert b["step"] == n_bb * n_tb + n_tb
        assert b["medusa"] == n_bb * n_tb
        assert b["total"] == (n_bb * n_tb + n_tb) + n_bb * n_tb
        # prefix cache: exactly one extra COW-copy signature, any mode
        for kw in ({}, {"spec": True}, {"medusa": True}):
            base = ServeEngine.signature_budget(n_bb, n_tb, **kw)["total"]
            plus = ServeEngine.signature_budget(n_bb, n_tb, prefix_cache=True, **kw)
            assert plus["copy"] == 1 and plus["total"] == base + 1

    def test_spec_and_medusa_mutually_exclusive(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            ServeEngine.signature_budget(2, 2, spec=True, medusa=True)


# ----------------------------------------------------------------- tracer


class TestTracer:
    def test_dropped_donation_is_visible_in_the_artifact(self):
        def step(state, batch):
            return state.astype(jnp.float32) * 2.0 + batch

        tp = trace_program(ProgramSpec(
            name="drop", fn=step,
            args=(jax.ShapeDtypeStruct((64, 64), jnp.int32),
                  jax.ShapeDtypeStruct((64, 64), jnp.float32)),
            donate_argnums=(0,),
        ))
        assert tp.trace_error is None
        assert tp.donated_bytes == 64 * 64 * 4
        assert tp.aliased_bytes == 0
        assert tp.donation_warnings  # jit said so, once, as a warning
        assert _rules(run_ir_rules(tp)) == ["DML601"]

    def test_clean_donation_aliases_fully(self):
        def step(state, batch):
            return state * 2.0 + batch

        spec = jax.ShapeDtypeStruct((64, 64), jnp.float32)
        tp = trace_program(ProgramSpec(
            name="clean", fn=step, args=(spec, spec), donate_argnums=(0,),
        ))
        assert tp.aliased_bytes == tp.donated_bytes == 64 * 64 * 4
        assert run_ir_rules(tp) == []

    def test_unbound_collective_axis_is_dml602(self):
        def step(x):
            return jax.lax.psum(x, axis_name="model")

        tp = trace_program(ProgramSpec(
            name="axes", fn=step,
            args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
            mesh=(("data", 1),),
        ))
        findings = run_ir_rules(tp)
        assert _rules(findings) == ["DML602"]
        assert "model" in findings[0].message

    def test_host_callback_is_dml603(self):
        def step(x):
            y = jax.pure_callback(
                lambda v: np.asarray(v), jax.ShapeDtypeStruct(x.shape, x.dtype), x
            )
            return y + 1.0

        tp = trace_program(ProgramSpec(
            name="cb", fn=step, args=(jax.ShapeDtypeStruct((4,), jnp.float32),),
        ))
        assert tp.callback_prims.get("pure_callback") == 1
        assert _rules(run_ir_rules(tp)) == ["DML603"]

    def test_hbm_budget_dml604_fires_and_clears(self):
        def step(x):
            return x @ x.T

        spec = jax.ShapeDtypeStruct((64, 64), jnp.float32)
        over = trace_program(ProgramSpec(
            name="hog", fn=step, args=(spec,), hbm_budget_bytes=1024,
        ))
        assert over.peak_bytes is not None and over.peak_bytes > 1024
        assert _rules(run_ir_rules(over)) == ["DML604"]
        within = trace_program(ProgramSpec(
            name="hog", fn=step, args=(spec,), hbm_budget_bytes=1 << 30,
        ))
        assert run_ir_rules(within) == []

    def test_signature_surface_dml605_needs_no_fn(self):
        over = trace_program(ProgramSpec(
            name="surface", fn=None, signature_surface=12, signature_budget=8,
        ))
        assert over.trace_error is None
        assert _rules(run_ir_rules(over)) == ["DML605"]
        within = trace_program(ProgramSpec(
            name="surface", fn=None, signature_surface=8, signature_budget=8,
        ))
        assert run_ir_rules(within) == []

    def test_broken_program_is_dml999(self):
        def step(x):
            raise RuntimeError("user code explodes at trace time")

        tp = trace_program(ProgramSpec(
            name="boom", fn=step, args=(jax.ShapeDtypeStruct((2,), jnp.float32),),
        ))
        assert "user code explodes" in tp.trace_error
        assert _rules(run_ir_rules(tp)) == ["DML999"]


# --------------------------------------------------------- fixture corpus


class TestFixtureCorpus:
    def test_dml601_bad_exactly_one(self):
        findings = verify_file(_fx("dml601_bad.py"))
        assert _rules(findings) == ["DML601"]
        assert findings[0].context == "dropped_donation_step"

    def test_dml601_clean_exactly_zero(self):
        assert verify_file(_fx("dml601_clean.py")) == []

    def test_dml604_bad_exactly_one(self):
        findings = verify_file(_fx("dml604_bad.py"))
        assert _rules(findings) == ["DML604"]

    def test_suppression_comment_reaches_the_ir_pass(self):
        # two identical callback programs; the one whose def line carries
        # ``# dmllint: disable=DML603`` is silent
        findings = verify_file(_fx("dml603_suppressed.py"))
        assert _rules(findings) == ["DML603"]
        assert findings[0].context == "flagged_callback_step"

    def test_dml205_provably_passes_the_dropped_donation_clean(self):
        """THE tentpole contrast: the AST donation rule sees the declared
        ``donate_argnums`` and stays quiet; only the IR pass (DML601)
        catches that the compiled executable dropped it."""
        ast_findings = lint_paths([_fx("dml601_bad.py")])
        assert "DML205" not in _rules(ast_findings)
        ir_findings = lint_paths([_fx("dml601_bad.py")], ir=True)
        assert "DML601" in _rules(ir_findings)

    def test_wildcard_select_and_ignore(self):
        assert set(expand_rule_ids(["DML6xx"])[0]) == {
            "DML601", "DML602", "DML603", "DML604", "DML605"
        }
        assert _rules(verify_file(_fx("dml601_bad.py"), select=["DML6xx"])) == ["DML601"]
        assert verify_file(_fx("dml601_bad.py"), ignore=["DML6xx"]) == []
        assert verify_file(_fx("dml601_bad.py"), select=["DML604"]) == []


# -------------------------------------------------------------- verify CLI


class TestVerifyCli:
    def test_json_schema_and_exact_counts(self, capsys):
        rc = verify_main([FIXTURES, "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["version"] == 1
        assert out["status"] == "findings"
        assert out["files_scanned"] == 4
        assert out["programs"] == 5
        assert out["counts"] == {"DML601": 1, "DML603": 1, "DML604": 1}

    def test_clean_file_exits_zero(self, capsys):
        rc = verify_main([_fx("dml601_clean.py"), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["status"] == "clean" and out["findings"] == []

    def test_text_mode_prints_findings(self, capsys):
        rc = verify_main([_fx("dml604_bad.py")])
        out = capsys.readouterr().out
        assert rc == 1 and "DML604" in out and "hbm_hog_step" in out

    def test_import_error_is_dml999_exit_2(self, tmp_path, capsys):
        bad = tmp_path / "broken_hook.py"
        bad.write_text(
            "raise RuntimeError('hook module explodes at import')\n"
            "def dml_verify_programs():\n    return []\n"
        )
        rc = verify_main([str(bad), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 2
        assert out["status"] == "trace_error"
        assert out["counts"] == {"DML999": 1}

    def test_hbm_budget_flag_fills_unset_budgets(self, capsys):
        # dml601_clean declares no budget; --hbm-budget 1 makes its step
        # exceed it -> DML604 appears without touching the fixture
        rc = verify_main([_fx("dml601_clean.py"), "--json", "--hbm-budget", "1"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1 and out["counts"] == {"DML604": 1}


# -------------------------------------------------------- lint integration


class TestLintIrIntegration:
    def test_warm_ir_run_is_byte_identical_to_cold(self, tmp_path, capsys):
        from dmlcloud_tpu.lint.cli import main as lint_main

        cache = str(tmp_path / "cache.json")
        argv = [FIXTURES, "--ir", "--cache", cache, "--select", "DML6xx"]
        rc_cold = lint_main(argv)
        cold = capsys.readouterr().out
        rc_warm = lint_main(argv)
        warm = capsys.readouterr().out
        assert rc_cold == rc_warm == 1
        assert warm == cold  # byte-identical through the incremental cache
        assert "DML601" in cold and "DML604" in cold

    def test_plain_and_ir_cache_states_never_cross(self, tmp_path, capsys):
        from dmlcloud_tpu.lint.cli import main as lint_main

        cache = str(tmp_path / "cache.json")
        sel = ["--select", "DML6xx"]
        assert lint_main([FIXTURES, "--cache", cache] + sel) == 0  # no IR pass
        capsys.readouterr()
        # a warm --ir run must NOT reuse the plain run's entries
        assert lint_main([FIXTURES, "--ir", "--cache", cache] + sel) == 1
        assert "DML601" in capsys.readouterr().out


# ------------------------------------------------------------ runtime arms


class _LinearStage(dml.TrainValStage):
    def pre_stage(self):
        rng = np.random.RandomState(0)
        w_true = rng.randn(4, 1).astype(np.float32)
        batches = []
        for s in (8, 5):
            x = rng.randn(s, 4).astype(np.float32)
            batches.append({"x": x, "y": x @ w_true})
        self.pipeline.register_model(
            "linear", apply_fn=lambda p, x: x @ p["w"],
            params={"w": jnp.zeros((4, 1))}, verbose=False,
        )
        self.pipeline.register_optimizer("sgd", optax.sgd(0.05))
        self.pipeline.register_dataset("train", batches, verbose=False)

    def step(self, state, batch):
        from dmlcloud_tpu.compile import buckets as bk

        pred = state.apply_fn(state.params, batch["x"])
        per = jnp.sum((pred - batch["y"]) ** 2, axis=-1)
        if "sample_mask" in batch:
            return bk.masked_mean(per, batch["sample_mask"])
        return jnp.mean(per)

    def val_epoch(self):
        pass


def _pipeline(**kw):
    from dmlcloud_tpu.parallel import mesh as mesh_lib

    p = dml.TrainingPipeline(name="verify-test", precompile=True,
                             buckets=(8,), **kw)
    p.set_mesh(mesh_lib.create_mesh({"data": 1}, devices=jax.devices()[:1]))
    p.append_stage(_LinearStage(), max_epochs=1)
    return p


class TestPipelineArm:
    def test_warn_mode_clean_run_records_zero_findings(self, single_runtime):
        p = _pipeline(verify="warn")
        p.run()
        assert p.verify_findings == []

    def test_error_mode_raises_on_hbm_budget(self, single_runtime):
        p = _pipeline(verify="error", hbm_budget=1)
        with pytest.raises(LintError, match="DML604"):
            p.run()
        assert "DML604" in _rules(p.verify_findings)

    def test_invalid_mode_rejected(self):
        with pytest.raises(ValueError, match="verify"):
            dml.TrainingPipeline(name="bad", verify="loud")


class TestEngineArm:
    def test_clean_engine_verifies_with_zero_findings(self, tiny_model):
        model, params = tiny_model
        eng = ServeEngine(model, params, num_blocks=64, block_size=4,
                          max_slots=2, prefill_chunk=8, verify="warn")
        assert eng.verify_findings == []
        # the DML605 lock: the independently enumerated surface equals the
        # centralized budget the TraceGuards are armed with
        assert eng._enumerate_signature_surface() == eng.max_signatures

    def test_error_mode_raises_on_hbm_budget(self, tiny_model):
        model, params = tiny_model
        with pytest.raises(LintError, match="DML604"):
            ServeEngine(model, params, num_blocks=64, block_size=4,
                        max_slots=2, prefill_chunk=8,
                        verify="error", hbm_budget=1000)

    def test_invalid_mode_rejected(self, tiny_model):
        model, params = tiny_model
        with pytest.raises(ValueError, match="verify"):
            ServeEngine(model, params, num_blocks=64, block_size=4,
                        max_slots=2, verify="loud")

    def test_journal_records_preflight_spans(self, tmp_path):
        from dmlcloud_tpu.telemetry.journal import SpanJournal, activate, deactivate

        j = SpanJournal(tmp_path)
        activate(j)
        try:
            findings = verify_programs([ProgramSpec(
                name="journaled", fn=lambda x: x * 2.0,
                args=(jax.ShapeDtypeStruct((4,), jnp.float32),),
            )])
        finally:
            deactivate()
        j.close()
        recs = [json.loads(line) for line in
                (tmp_path / "journal-rank0.jsonl").read_text().splitlines()]
        assert findings == []
        pre = [r for r in recs if r["kind"] == "preflight"]
        assert len(pre) == 1
        assert pre[0]["label"] == "journaled"
