"""dmlcloud_tpu.lint: fixture corpus per rule, suppression comments, CLI
--json schema, TraceGuard retrace detection, and the pipeline's lint= arm.

The fixture files under tests/lint_fixtures/ are static data (never
imported): each bad file must produce findings for exactly its own rule,
each clean file must produce none.
"""

import json
import logging
from pathlib import Path

import pytest

from dmlcloud_tpu.lint import (
    RULES,
    Finding,
    LintError,
    RetraceError,
    TraceGuard,
    lint_file,
    lint_paths,
    lint_source,
)
from dmlcloud_tpu.lint.cli import main as lint_cli

FIXTURES = Path(__file__).parent / "lint_fixtures"

#: rule -> expected number of findings in its bad fixture
BAD_EXPECT = {
    "DML101": 6,
    "DML102": 3,
    "DML103": 3,
    "DML104": 4,
    "DML105": 2,
    "DML106": 2,
    "DML107": 3,
    "DML108": 5,
    "DML201": 4,
    "DML202": 3,
    "DML203": 2,
    "DML204": 3,
    "DML205": 3,
    "DML206": 3,
    "DML207": 3,
    "DML208": 4,
    "DML209": 5,
    "DML210": 4,
    "DML211": 4,
    "DML212": 4,
    "DML213": 4,
    "DML214": 4,
    "DML215": 4,
    "DML301": 2,
    "DML302": 2,
}


class TestFixtureCorpus:
    @pytest.mark.parametrize("rule_id", sorted(BAD_EXPECT))
    def test_bad_fixture_flags_exactly_its_rule(self, rule_id):
        findings = lint_file(FIXTURES / f"{rule_id.lower()}_bad.py")
        assert findings, f"{rule_id} bad fixture produced no findings"
        assert {f.rule for f in findings} == {rule_id}, [f.format() for f in findings]
        assert len(findings) == BAD_EXPECT[rule_id], [f.format() for f in findings]

    @pytest.mark.parametrize("rule_id", sorted(BAD_EXPECT))
    def test_clean_fixture_is_clean(self, rule_id):
        findings = lint_file(FIXTURES / f"{rule_id.lower()}_clean.py")
        assert findings == [], [f.format() for f in findings]

    def test_every_rule_has_a_fixture_pair(self):
        for rule_id in RULES:
            if rule_id == "DML999":
                continue
            assert (FIXTURES / f"{rule_id.lower()}_bad.py").is_file()
            assert (FIXTURES / f"{rule_id.lower()}_clean.py").is_file()

    def test_findings_report_real_locations(self):
        findings = lint_file(FIXTURES / "dml101_bad.py")
        src_lines = (FIXTURES / "dml101_bad.py").read_text().splitlines()
        for f in findings:
            assert 1 <= f.line <= len(src_lines)
            assert "BAD" in src_lines[f.line - 1], f.format()
            assert f.context  # all corpus hazards sit inside functions


class TestSuppression:
    def test_suppressed_fixture_is_clean(self):
        assert lint_file(FIXTURES / "suppressed.py") == []

    def test_same_line_directive(self):
        src = (
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        v = loss.item()  # dmllint: disable=DML101 -- why\n"
        )
        assert lint_source(src) == []
        # and without the directive the finding is real
        assert [f.rule for f in lint_source(src.replace("  # dmllint: disable=DML101 -- why", ""))] == ["DML101"]

    def test_next_line_directive(self):
        src = (
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        # dmllint: disable-next-line=DML101\n"
            "        v = loss.item()\n"
        )
        assert lint_source(src) == []

    def test_file_wide_directive(self):
        src = (
            "# dmllint: disable-file=DML101\n"
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        v = loss.item()\n"
            "        w = other.item()\n"
        )
        assert lint_source(src) == []

    def test_disable_all(self):
        src = (
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        v = loss.item()  # dmllint: disable=all\n"
        )
        assert lint_source(src) == []

    def test_unrelated_id_does_not_suppress(self):
        src = (
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        v = loss.item()  # dmllint: disable=DML104\n"
        )
        assert [f.rule for f in lint_source(src)] == ["DML101"]


class TestEngineEdges:
    def test_parse_error_is_a_finding(self):
        findings = lint_source("def broken(:\n", path="x.py")
        assert len(findings) == 1
        assert findings[0].rule == "DML999"
        assert "parse" in findings[0].message

    def test_select_and_ignore(self):
        bad = (FIXTURES / "dml101_bad.py").read_text()
        assert lint_source(bad, select=["DML104"]) == []
        assert lint_source(bad, ignore=["DML101"]) == []
        assert {f.rule for f in lint_source(bad, select=["DML101"])} == {"DML101"}

    def test_non_hazard_context_is_not_linted(self):
        # float()/np.random/.item() outside step/epoch contexts lint clean:
        # the rules are contract rules, not style rules
        src = (
            "import numpy as np\n"
            "def load(path):\n"
            "    rng = np.random.RandomState(0)\n"
            "    v = float(rng.randn(1).item())\n"
            "    return v\n"
        )
        assert lint_source(src) == []

    def test_measure_block_exempts_sync(self):
        src = (
            "import jax\n"
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        with self._stall.measure():\n"
            "            v = jax.device_get(metrics)\n"
        )
        assert lint_source(src) == []

    def test_lint_paths_walks_directories(self):
        findings = lint_paths([FIXTURES])
        # the flat {rule}_bad.py corpus plus the DML5xx whole-program
        # packages (dml501/..dml504/); DML502 also fires on dml211_bad.py —
        # the call-graph pass sees the same unguarded scatter the vocab
        # rule flags, which is exactly the subsumption contract
        expected = set(BAD_EXPECT) | {"DML501", "DML502", "DML503", "DML504"}
        assert {f.rule for f in findings} == expected
        assert findings == sorted(findings, key=Finding.sort_key)


class TestCLI:
    def test_json_schema_on_bad_fixture(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml101_bad.py"), "--json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["version"] == 2
        assert payload["status"] == "findings"
        assert payload["files_scanned"] == 1
        assert payload["counts"] == {"DML101": BAD_EXPECT["DML101"]}
        assert len(payload["findings"]) == BAD_EXPECT["DML101"]
        for f in payload["findings"]:
            assert set(f) == {"rule", "path", "line", "col", "message", "context"}
            assert isinstance(f["line"], int) and f["line"] >= 1
        # stable ordering: sorted by (path, line, col, rule)
        keys = [(f["path"], f["line"], f["col"], f["rule"]) for f in payload["findings"]]
        assert keys == sorted(keys)

    def test_json_clean_exit_zero(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml101_clean.py"), "--json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"] == [] and payload["counts"] == {}

    def test_human_output(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml103_bad.py")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "DML103" in out and "dml103_bad.py" in out
        assert "3 finding(s)" in out

    def test_list_rules(self, capsys):
        assert lint_cli(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule_id in BAD_EXPECT:
            assert rule_id in out

    def test_select_flag(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml101_bad.py"), "--select", "DML104", "--json"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["findings"] == []

    def test_unknown_rule_id_is_usage_error(self, capsys):
        assert lint_cli([str(FIXTURES), "--select", "DML777"]) == 2

    def test_github_format(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml201_bad.py"), "--format=github"])
        out = capsys.readouterr().out
        assert rc == 1
        lines = [l for l in out.splitlines() if l.startswith("::error")]
        assert len(lines) == BAD_EXPECT["DML201"]
        assert lines[0].startswith("::error file=")
        assert ",line=" in lines[0] and "title=DML201::" in lines[0]
        assert "::notice::" in out

    def test_github_format_clean(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml201_clean.py"), "--format=github"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "::error" not in out and "0 finding(s)" in out

    def test_json_flag_is_format_shorthand(self, capsys):
        lint_cli([str(FIXTURES / "dml101_bad.py"), "--format=json"])
        via_format = capsys.readouterr().out
        lint_cli([str(FIXTURES / "dml101_bad.py"), "--json"])
        via_flag = capsys.readouterr().out
        assert via_format == via_flag

    def test_conflicting_formats_rejected(self, capsys):
        assert lint_cli([str(FIXTURES), "--json", "--format=github"]) == 2

    def test_jobs_flag(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml201_bad.py"), "--jobs", "2", "--json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["counts"] == {"DML201": BAD_EXPECT["DML201"]}
        assert lint_cli([str(FIXTURES), "--jobs", "0"]) == 2

    def test_select_family_wildcard_cli(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml301_bad.py"), "--select", "DML3xx", "--json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert set(payload["counts"]) == {"DML301"}


class TestDataflowAliasing:
    """Acceptance: DML201/DML202 resolve axis names through at least one
    level of assignment/aliasing — not just literals at the call site."""

    def test_alias_fixture_is_clean(self):
        assert lint_file(FIXTURES / "sharding_alias_clean.py") == []

    def test_axis_through_assignment_flags_unknown(self):
        src = (
            "import jax\n"
            "from dmlcloud_tpu.parallel.mesh import create_mesh\n"
            'axes = {"data": -1, "rows": 2}\n'
            "mesh = create_mesh(axes)\n"
            "@jax.jit\n"
            "def f(x):\n"
            '    ax = "cols"\n'
            "    return jax.lax.psum(x, ax)\n"
        )
        assert [f.rule for f in lint_source(src)] == ["DML201"]
        # and the axis the alias chain DOES declare is accepted
        assert lint_source(src.replace('"cols"', '"rows"')) == []

    def test_spec_tuple_through_assignment(self):
        src = (
            "import jax\n"
            "from jax.sharding import PartitionSpec as P\n"
            "from dmlcloud_tpu.parallel.mesh import create_mesh\n"
            "def body(a, b):\n"
            "    return a + b\n"
            'mesh = create_mesh({"data": 8})\n'
            "specs = (P('data'),)\n"
            "f = jax.shard_map(body, mesh=mesh, in_specs=specs, out_specs=P())\n"
        )
        findings = lint_source(src)
        assert [f.rule for f in findings] == ["DML202"], [f.format() for f in findings]
        assert "2 positional argument" in findings[0].message

    def test_unresolvable_axis_never_guessed(self):
        src = (
            "import jax\n"
            "def helper(x, axis_name):\n"
            "    return jax.lax.psum(x, axis_name)\n"
        )
        assert lint_source(src) == []

    def test_local_mesh_literal_beats_builtin_vocabulary(self):
        # 'model' is in the framework vocabulary, but THIS shard_map's mesh
        # provably has only 'data' — flow beats vocabulary
        src = (
            "import jax\n"
            "from jax.sharding import PartitionSpec as P\n"
            "from dmlcloud_tpu.parallel.mesh import create_mesh\n"
            "def body(a):\n"
            "    return a\n"
            'mesh = create_mesh({"data": 8})\n'
            "f = jax.shard_map(body, mesh=mesh, in_specs=(P('model'),), out_specs=P())\n"
        )
        assert [f.rule for f in lint_source(src)] == ["DML202"]


class TestProjectRegistry:
    """Mesh axes declared in one file legitimise collectives in another
    when linted together (lint_paths' two-pass project context)."""

    def test_cross_file_axis_declaration(self, tmp_path):
        (tmp_path / "meshes.py").write_text(
            "from dmlcloud_tpu.parallel.mesh import create_mesh\n"
            'mesh = create_mesh({"data": -1, "widgets": 4})\n'
        )
        (tmp_path / "ops.py").write_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            '    return jax.lax.psum(x, "widgets")\n'
        )
        assert lint_paths([tmp_path]) == []
        # alone, ops.py cannot know about 'widgets'
        assert [f.rule for f in lint_paths([tmp_path / "ops.py"])] == ["DML201"]

    def test_jobs_parallel_matches_serial(self, tmp_path):
        serial = lint_paths([FIXTURES])
        parallel = lint_paths([FIXTURES], jobs=2)
        assert [f.format() for f in parallel] == [f.format() for f in serial]


class TestWildcards:
    """Family wildcards (DML2xx) in suppression comments and selection, and
    their interaction — acceptance for the suppression/selection satellite."""

    BAD_AXIS = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        '    return jax.lax.psum(x, "bogus")\n'
    )

    def test_family_wildcard_suppression(self):
        src = self.BAD_AXIS.replace(
            'jax.lax.psum(x, "bogus")',
            'jax.lax.psum(x, "bogus")  # dmllint: disable=DML2xx -- staged mesh',
        )
        assert lint_source(src) == []
        # the wildcard covers its own family only
        assert [f.rule for f in lint_source(self.BAD_AXIS, select=["DML2xx"])] == ["DML201"]

    def test_file_wide_directive_beats_select(self):
        # --select DML201 must NOT resurrect a finding the file disabled
        src = "# dmllint: disable-file=DML201\n" + self.BAD_AXIS
        assert lint_source(src, select=["DML201"]) == []

    def test_select_family_wildcard(self):
        bad = (FIXTURES / "dml201_bad.py").read_text()
        assert {f.rule for f in lint_source(bad, select=["DML2xx"])} == {"DML201"}
        assert lint_source(bad, select=["DML1xx"]) == []
        assert lint_source(bad, ignore=["DML2xx"]) == []

    def test_expand_rule_ids(self):
        from dmlcloud_tpu.lint.engine import expand_rule_ids

        expanded, unknown = expand_rule_ids(["DML3xx", "DML101", "DML9xx"])
        assert expanded == ["DML301", "DML302", "DML101"]
        assert unknown == ["DML9xx"]


class TestTraceGuard:
    def test_flags_retrace_on_cpu(self):
        import jax
        import jax.numpy as jnp

        guarded = TraceGuard(jax.jit(lambda x: x * 2), max_traces=1)
        guarded(jnp.ones(3))
        guarded(jnp.ones(3))  # same shape: cached, fine
        assert guarded.cache_size() == 1
        with pytest.raises(RetraceError, match="DML104"):
            guarded(jnp.ones(4))  # new shape: retrace

    def test_warn_mode_logs_once_per_growth(self, caplog):
        import jax
        import jax.numpy as jnp

        guarded = TraceGuard(jax.jit(lambda x: x + 1), max_traces=1, action="warn", name="step")
        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu.lint.traceguard"):
            guarded(jnp.ones(2))
            guarded(jnp.ones(3))
            guarded(jnp.ones(3))  # no growth: no second warning
        msgs = [r for r in caplog.records if "TraceGuard[step]" in r.getMessage()]
        assert len(msgs) == 1

    def test_shape_buckets_allowed_by_max_traces(self):
        import jax
        import jax.numpy as jnp

        guarded = TraceGuard(jax.jit(lambda x: x.sum()), max_traces=2)
        guarded(jnp.ones(2))
        guarded(jnp.ones(4))  # second bucket: allowed
        assert guarded.calls == 2

    def test_unjitted_callable_passes_through(self):
        guarded = TraceGuard(lambda x: x + 1, max_traces=1)
        assert guarded(1) == 2 and guarded(2) == 3
        assert guarded.cache_size() is None

    def test_rejects_bad_args(self):
        with pytest.raises(ValueError):
            TraceGuard(lambda x: x, action="explode")
        with pytest.raises(ValueError):
            TraceGuard(lambda x: x, max_traces=0)


def _make_bad_stage_cls():
    from dmlcloud_tpu import TrainValStage

    class ItemHappyStage(TrainValStage):
        def train_epoch(self):
            for batch in self.ds:
                self.state, metrics = self._train_step_fn(self.state, batch)
                self.track_reduce("loss", metrics["loss"].item())

    return ItemHappyStage


class TestPipelineLintArm:
    def test_error_mode_raises_before_any_device_work(self):
        from dmlcloud_tpu import TrainingPipeline

        pipeline = TrainingPipeline(lint="error")
        pipeline.append_stage(_make_bad_stage_cls()(), max_epochs=1)
        with pytest.raises(LintError, match="DML101") as exc:
            pipeline.run()
        assert exc.value.findings and exc.value.findings[0].rule == "DML101"

    def test_warn_mode_logs_and_continues(self, caplog):
        from dmlcloud_tpu import TrainingPipeline

        pipeline = TrainingPipeline(lint="warn")
        pipeline.append_stage(_make_bad_stage_cls()(), max_epochs=1)
        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu"):
            pipeline._lint_stages()
        assert any("DML101" in r.getMessage() for r in caplog.records)

    def test_clean_stage_passes_error_mode(self):
        from dmlcloud_tpu import TrainingPipeline, TrainValStage

        class FineStage(TrainValStage):
            def step(self, state, batch):
                return state.apply_fn(state.params, batch).mean()

        pipeline = TrainingPipeline(lint="error")
        pipeline.append_stage(FineStage(), max_epochs=1)
        pipeline._lint_stages()  # no raise

    def test_invalid_mode_rejected(self):
        from dmlcloud_tpu import TrainingPipeline

        with pytest.raises(ValueError):
            TrainingPipeline(lint="maybe")
