"""dmlcloud_tpu.lint: fixture corpus per rule, suppression comments, CLI
--json schema, TraceGuard retrace detection, and the pipeline's lint= arm.

The fixture files under tests/lint_fixtures/ are static data (never
imported): each bad file must produce findings for exactly its own rule,
each clean file must produce none.
"""

import json
import logging
from pathlib import Path

import pytest

from dmlcloud_tpu.lint import (
    RULES,
    Finding,
    LintError,
    RetraceError,
    TraceGuard,
    lint_file,
    lint_paths,
    lint_source,
)
from dmlcloud_tpu.lint.cli import main as lint_cli

FIXTURES = Path(__file__).parent / "lint_fixtures"

#: rule -> expected number of findings in its bad fixture
BAD_EXPECT = {
    "DML101": 6,
    "DML102": 3,
    "DML103": 3,
    "DML104": 4,
    "DML105": 2,
    "DML106": 2,
    "DML107": 3,
    "DML108": 5,
}


class TestFixtureCorpus:
    @pytest.mark.parametrize("rule_id", sorted(BAD_EXPECT))
    def test_bad_fixture_flags_exactly_its_rule(self, rule_id):
        findings = lint_file(FIXTURES / f"{rule_id.lower()}_bad.py")
        assert findings, f"{rule_id} bad fixture produced no findings"
        assert {f.rule for f in findings} == {rule_id}, [f.format() for f in findings]
        assert len(findings) == BAD_EXPECT[rule_id], [f.format() for f in findings]

    @pytest.mark.parametrize("rule_id", sorted(BAD_EXPECT))
    def test_clean_fixture_is_clean(self, rule_id):
        findings = lint_file(FIXTURES / f"{rule_id.lower()}_clean.py")
        assert findings == [], [f.format() for f in findings]

    def test_every_rule_has_a_fixture_pair(self):
        for rule_id in RULES:
            if rule_id == "DML999":
                continue
            assert (FIXTURES / f"{rule_id.lower()}_bad.py").is_file()
            assert (FIXTURES / f"{rule_id.lower()}_clean.py").is_file()

    def test_findings_report_real_locations(self):
        findings = lint_file(FIXTURES / "dml101_bad.py")
        src_lines = (FIXTURES / "dml101_bad.py").read_text().splitlines()
        for f in findings:
            assert 1 <= f.line <= len(src_lines)
            assert "BAD" in src_lines[f.line - 1], f.format()
            assert f.context  # all corpus hazards sit inside functions


class TestSuppression:
    def test_suppressed_fixture_is_clean(self):
        assert lint_file(FIXTURES / "suppressed.py") == []

    def test_same_line_directive(self):
        src = (
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        v = loss.item()  # dmllint: disable=DML101 -- why\n"
        )
        assert lint_source(src) == []
        # and without the directive the finding is real
        assert [f.rule for f in lint_source(src.replace("  # dmllint: disable=DML101 -- why", ""))] == ["DML101"]

    def test_next_line_directive(self):
        src = (
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        # dmllint: disable-next-line=DML101\n"
            "        v = loss.item()\n"
        )
        assert lint_source(src) == []

    def test_file_wide_directive(self):
        src = (
            "# dmllint: disable-file=DML101\n"
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        v = loss.item()\n"
            "        w = other.item()\n"
        )
        assert lint_source(src) == []

    def test_disable_all(self):
        src = (
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        v = loss.item()  # dmllint: disable=all\n"
        )
        assert lint_source(src) == []

    def test_unrelated_id_does_not_suppress(self):
        src = (
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        v = loss.item()  # dmllint: disable=DML104\n"
        )
        assert [f.rule for f in lint_source(src)] == ["DML101"]


class TestEngineEdges:
    def test_parse_error_is_a_finding(self):
        findings = lint_source("def broken(:\n", path="x.py")
        assert len(findings) == 1
        assert findings[0].rule == "DML999"
        assert "parse" in findings[0].message

    def test_select_and_ignore(self):
        bad = (FIXTURES / "dml101_bad.py").read_text()
        assert lint_source(bad, select=["DML104"]) == []
        assert lint_source(bad, ignore=["DML101"]) == []
        assert {f.rule for f in lint_source(bad, select=["DML101"])} == {"DML101"}

    def test_non_hazard_context_is_not_linted(self):
        # float()/np.random/.item() outside step/epoch contexts lint clean:
        # the rules are contract rules, not style rules
        src = (
            "import numpy as np\n"
            "def load(path):\n"
            "    rng = np.random.RandomState(0)\n"
            "    v = float(rng.randn(1).item())\n"
            "    return v\n"
        )
        assert lint_source(src) == []

    def test_measure_block_exempts_sync(self):
        src = (
            "import jax\n"
            "class S(TrainValStage):\n"
            "    def train_epoch(self):\n"
            "        with self._stall.measure():\n"
            "            v = jax.device_get(metrics)\n"
        )
        assert lint_source(src) == []

    def test_lint_paths_walks_directories(self):
        findings = lint_paths([FIXTURES])
        assert {f.rule for f in findings} == set(BAD_EXPECT)
        assert findings == sorted(findings, key=Finding.sort_key)


class TestCLI:
    def test_json_schema_on_bad_fixture(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml101_bad.py"), "--json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["version"] == 1
        assert payload["files_scanned"] == 1
        assert payload["counts"] == {"DML101": BAD_EXPECT["DML101"]}
        assert len(payload["findings"]) == BAD_EXPECT["DML101"]
        for f in payload["findings"]:
            assert set(f) == {"rule", "path", "line", "col", "message", "context"}
            assert isinstance(f["line"], int) and f["line"] >= 1
        # stable ordering: sorted by (path, line, col, rule)
        keys = [(f["path"], f["line"], f["col"], f["rule"]) for f in payload["findings"]]
        assert keys == sorted(keys)

    def test_json_clean_exit_zero(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml101_clean.py"), "--json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"] == [] and payload["counts"] == {}

    def test_human_output(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml103_bad.py")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "DML103" in out and "dml103_bad.py" in out
        assert "3 finding(s)" in out

    def test_list_rules(self, capsys):
        assert lint_cli(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule_id in BAD_EXPECT:
            assert rule_id in out

    def test_select_flag(self, capsys):
        rc = lint_cli([str(FIXTURES / "dml101_bad.py"), "--select", "DML104", "--json"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["findings"] == []

    def test_unknown_rule_id_is_usage_error(self, capsys):
        assert lint_cli([str(FIXTURES), "--select", "DML777"]) == 2


class TestTraceGuard:
    def test_flags_retrace_on_cpu(self):
        import jax
        import jax.numpy as jnp

        guarded = TraceGuard(jax.jit(lambda x: x * 2), max_traces=1)
        guarded(jnp.ones(3))
        guarded(jnp.ones(3))  # same shape: cached, fine
        assert guarded.cache_size() == 1
        with pytest.raises(RetraceError, match="DML104"):
            guarded(jnp.ones(4))  # new shape: retrace

    def test_warn_mode_logs_once_per_growth(self, caplog):
        import jax
        import jax.numpy as jnp

        guarded = TraceGuard(jax.jit(lambda x: x + 1), max_traces=1, action="warn", name="step")
        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu.lint.traceguard"):
            guarded(jnp.ones(2))
            guarded(jnp.ones(3))
            guarded(jnp.ones(3))  # no growth: no second warning
        msgs = [r for r in caplog.records if "TraceGuard[step]" in r.getMessage()]
        assert len(msgs) == 1

    def test_shape_buckets_allowed_by_max_traces(self):
        import jax
        import jax.numpy as jnp

        guarded = TraceGuard(jax.jit(lambda x: x.sum()), max_traces=2)
        guarded(jnp.ones(2))
        guarded(jnp.ones(4))  # second bucket: allowed
        assert guarded.calls == 2

    def test_unjitted_callable_passes_through(self):
        guarded = TraceGuard(lambda x: x + 1, max_traces=1)
        assert guarded(1) == 2 and guarded(2) == 3
        assert guarded.cache_size() is None

    def test_rejects_bad_args(self):
        with pytest.raises(ValueError):
            TraceGuard(lambda x: x, action="explode")
        with pytest.raises(ValueError):
            TraceGuard(lambda x: x, max_traces=0)


def _make_bad_stage_cls():
    from dmlcloud_tpu import TrainValStage

    class ItemHappyStage(TrainValStage):
        def train_epoch(self):
            for batch in self.ds:
                self.state, metrics = self._train_step_fn(self.state, batch)
                self.track_reduce("loss", metrics["loss"].item())

    return ItemHappyStage


class TestPipelineLintArm:
    def test_error_mode_raises_before_any_device_work(self):
        from dmlcloud_tpu import TrainingPipeline

        pipeline = TrainingPipeline(lint="error")
        pipeline.append_stage(_make_bad_stage_cls()(), max_epochs=1)
        with pytest.raises(LintError, match="DML101") as exc:
            pipeline.run()
        assert exc.value.findings and exc.value.findings[0].rule == "DML101"

    def test_warn_mode_logs_and_continues(self, caplog):
        from dmlcloud_tpu import TrainingPipeline

        pipeline = TrainingPipeline(lint="warn")
        pipeline.append_stage(_make_bad_stage_cls()(), max_epochs=1)
        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu"):
            pipeline._lint_stages()
        assert any("DML101" in r.getMessage() for r in caplog.records)

    def test_clean_stage_passes_error_mode(self):
        from dmlcloud_tpu import TrainingPipeline, TrainValStage

        class FineStage(TrainValStage):
            def step(self, state, batch):
                return state.apply_fn(state.params, batch).mean()

        pipeline = TrainingPipeline(lint="error")
        pipeline.append_stage(FineStage(), max_epochs=1)
        pipeline._lint_stages()  # no raise

    def test_invalid_mode_rejected(self):
        from dmlcloud_tpu import TrainingPipeline

        with pytest.raises(ValueError):
            TrainingPipeline(lint="maybe")
