"""L1 runtime: accessors, root helpers, object collectives (single process),
init ladder detection. Multi-process behavior is exercised via the KV-store
code paths only when a coordination service exists; here world_size==1
degenerates exactly like the reference's dummy process group."""

import os

import pytest

from dmlcloud_tpu.parallel import runtime
from dmlcloud_tpu.utils import slurm


def test_init_single(single_runtime):
    assert runtime.is_initialized()
    assert runtime.rank() == 0
    assert runtime.world_size() == 1
    assert runtime.local_rank() == 0
    assert runtime.local_world_size() == 1
    assert runtime.is_root()


def test_init_auto_falls_back_to_single(single_runtime):
    runtime.deinitialize()
    backend = runtime.init_auto()
    assert backend == "single"


def test_root_only(single_runtime):
    calls = []

    @runtime.root_only
    def fn(x):
        calls.append(x)
        return x * 2

    assert fn(21) == 42
    assert calls == [21]


def test_root_first(single_runtime):
    with runtime.root_first():
        pass  # single process: no deadlock, no error


def test_object_collectives_single(single_runtime):
    assert runtime.broadcast_object({"a": 1}) == {"a": 1}
    assert runtime.all_gather_object(7) == [7]
    assert runtime.gather_object("x") == ["x"]


def test_barrier_single_noop(single_runtime):
    runtime.barrier("test", timeout=1)


def test_device_accessors(single_runtime):
    assert runtime.device_count() == 8
    assert runtime.local_device_count() == 8


def test_slurm_detection(monkeypatch):
    monkeypatch.setenv("SLURM_PROCID", "3")
    monkeypatch.setenv("SLURM_NTASKS", "8")
    monkeypatch.setenv("SLURM_LOCALID", "1")
    monkeypatch.setenv("SLURM_NODEID", "1")
    monkeypatch.setenv("SLURM_STEP_TASKS_PER_NODE", "4(x2)")
    monkeypatch.setenv("SLURM_JOB_NODELIST", "node[017-018]")
    assert runtime.has_slurm()
    assert slurm.slurm_rank() == 3
    assert slurm.slurm_world_size() == 8
    assert slurm.slurm_tasks_per_node() == 4
    assert slurm.slurm_head_node() == "node017"


def test_has_environment(monkeypatch):
    assert not runtime.has_environment() or "JAX_COORDINATOR_ADDRESS" in os.environ
    monkeypatch.setenv("DMLCLOUD_TPU_COORDINATOR", "localhost:1234")
    assert runtime.has_environment()


def test_print_helpers(single_runtime, capsys):
    runtime.print_root("hello")
    runtime.print_worker("there")
    out = capsys.readouterr().out
    assert "hello" in out
    assert "Worker 0 (0.0): there" in out
