"""L1 runtime: accessors, root helpers, object collectives (single process),
init ladder detection. Multi-process behavior is exercised via the KV-store
code paths only when a coordination service exists; here world_size==1
degenerates exactly like the reference's dummy process group."""

import os

import pytest

from dmlcloud_tpu.parallel import runtime
from dmlcloud_tpu.utils import slurm


def test_init_single(single_runtime):
    assert runtime.is_initialized()
    assert runtime.rank() == 0
    assert runtime.world_size() == 1
    assert runtime.local_rank() == 0
    assert runtime.local_world_size() == 1
    assert runtime.is_root()


def test_init_auto_falls_back_to_single(single_runtime):
    runtime.deinitialize()
    backend = runtime.init_auto()
    assert backend == "single"


def test_root_only(single_runtime):
    calls = []

    @runtime.root_only
    def fn(x):
        calls.append(x)
        return x * 2

    assert fn(21) == 42
    assert calls == [21]


def test_root_first(single_runtime):
    with runtime.root_first():
        pass  # single process: no deadlock, no error


def test_object_collectives_single(single_runtime):
    assert runtime.broadcast_object({"a": 1}) == {"a": 1}
    assert runtime.all_gather_object(7) == [7]
    assert runtime.gather_object("x") == ["x"]


def test_barrier_single_noop(single_runtime):
    runtime.barrier("test", timeout=1)


def test_device_accessors(single_runtime):
    assert runtime.device_count() == 8
    assert runtime.local_device_count() == 8


def test_slurm_detection(monkeypatch):
    monkeypatch.setenv("SLURM_PROCID", "3")
    monkeypatch.setenv("SLURM_NTASKS", "8")
    monkeypatch.setenv("SLURM_LOCALID", "1")
    monkeypatch.setenv("SLURM_NODEID", "1")
    monkeypatch.setenv("SLURM_STEP_TASKS_PER_NODE", "4(x2)")
    monkeypatch.setenv("SLURM_JOB_NODELIST", "node[017-018]")
    assert runtime.has_slurm()
    assert slurm.slurm_rank() == 3
    assert slurm.slurm_world_size() == 8
    assert slurm.slurm_tasks_per_node() == 4
    assert slurm.slurm_head_node() == "node017"


def test_has_environment(monkeypatch):
    assert not runtime.has_environment() or "JAX_COORDINATOR_ADDRESS" in os.environ
    monkeypatch.setenv("DMLCLOUD_TPU_COORDINATOR", "localhost:1234")
    assert runtime.has_environment()


def test_print_helpers(single_runtime, capsys):
    runtime.print_root("hello")
    runtime.print_worker("there")
    out = capsys.readouterr().out
    assert "hello" in out
    assert "Worker 0 (0.0): there" in out


class _FakeClient:
    """Coordination-client stub for barrier logic: records arrival keys and
    raises a scripted error from wait_at_barrier."""

    def __init__(self, wait_error=None, present_keys=()):
        self.kv = {k: "1" for k in present_keys}
        self.wait_error = wait_error

    def key_value_set(self, key, value):
        self.kv[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        if key in self.kv:
            return self.kv[key]
        raise RuntimeError("DEADLINE_EXCEEDED: key not found")

    def key_value_delete(self, key):
        self.kv.pop(key, None)

    def wait_at_barrier(self, barrier_id, timeout_in_ms):
        if self.wait_error is not None:
            raise self.wait_error


class TestBarrierDiagnostics:
    """barrier() behavior on failure, driven through a fake client so the
    classification logic is testable at world size 1."""

    def _run_barrier(self, monkeypatch, client, world=4, my_rank=0):
        monkeypatch.setattr(runtime, "_client", lambda: client)
        monkeypatch.setattr(runtime, "world_size", lambda: world)
        monkeypatch.setattr(runtime, "rank", lambda: my_rank)
        runtime.barrier("unit", timeout=1)

    def test_timeout_names_stragglers(self, single_runtime, monkeypatch):
        client = _FakeClient(wait_error=RuntimeError("DEADLINE_EXCEEDED while waiting"))
        # ranks 1..3 never arrive: only this rank's key gets set by barrier()
        with pytest.raises(runtime.BarrierTimeout) as exc:
            self._run_barrier(monkeypatch, client)
        # rank 0 arrived (its own key), 1..3 did not
        assert exc.value.stragglers == [1, 2, 3]
        assert "straggler" in str(exc.value)

    def test_timeout_with_all_arrived_reports_empty(self, single_runtime, monkeypatch):
        barrier_keys = [f"dmlcloud_tpu:unit:{runtime._seq['barrier'] + 1}/arrived/{r}" for r in range(4)]
        client = _FakeClient(
            wait_error=RuntimeError("deadline exceeded"), present_keys=barrier_keys
        )
        with pytest.raises(runtime.BarrierTimeout) as exc:
            self._run_barrier(monkeypatch, client)
        assert exc.value.stragglers == []
        assert "unknown" in str(exc.value)

    def test_non_timeout_error_not_misdiagnosed(self, single_runtime, monkeypatch):
        """A lost coordinator connection must re-raise as-is, not masquerade
        as a timeout with phantom stragglers."""
        client = _FakeClient(wait_error=ConnectionError("coordinator connection reset"))
        with pytest.raises(ConnectionError, match="connection reset"):
            self._run_barrier(monkeypatch, client)

    def test_success_leaves_arrival_key(self, single_runtime, monkeypatch):
        """Arrival keys persist after a successful barrier — deleting them
        would let a marginal-race prober misname arrived ranks."""
        monkeypatch.setattr(runtime, "_gc_barrier_ids", [])
        client = _FakeClient()
        self._run_barrier(monkeypatch, client)
        assert any("/arrived/0" in k for k in client.kv)

    def test_completed_barrier_keys_swept_one_barrier_later(self, single_runtime, monkeypatch):
        """The coordinator's KV store must not accrue O(barriers) arrival
        keys on long jobs: once a LATER barrier completes, every rank has
        provably left the earlier one, so the root sweeps its keys. The
        just-completed barrier's own keys stay (straggler-race safety)."""
        monkeypatch.setattr(runtime, "_gc_barrier_ids", [])
        client = _FakeClient()
        self._run_barrier(monkeypatch, client)
        first_keys = [k for k in client.kv if "/arrived/" in k]
        assert first_keys  # barrier 1's keys present after barrier 1
        self._run_barrier(monkeypatch, client)
        remaining = [k for k in client.kv if "/arrived/" in k]
        assert all(k not in remaining for k in first_keys)  # swept
        assert remaining  # barrier 2's own keys survive until barrier 3
        self._run_barrier(monkeypatch, client)
        assert all(k not in client.kv for k in remaining)

    def test_failed_barrier_does_not_sweep(self, single_runtime, monkeypatch):
        """A timed-out barrier must leave the previous barrier's keys alone —
        its straggler probe (and any retry's) may still need them."""
        monkeypatch.setattr(runtime, "_gc_barrier_ids", [])
        client = _FakeClient()
        self._run_barrier(monkeypatch, client)
        first_keys = [k for k in client.kv if "/arrived/" in k]
        client.wait_error = RuntimeError("DEADLINE_EXCEEDED while waiting")
        with pytest.raises(runtime.BarrierTimeout):
            self._run_barrier(monkeypatch, client)
        assert all(k in client.kv for k in first_keys)

    def test_non_root_does_not_sweep(self, single_runtime, monkeypatch):
        monkeypatch.setattr(runtime, "_gc_barrier_ids", [])
        client = _FakeClient()
        self._run_barrier(monkeypatch, client, my_rank=1)
        first_keys = [k for k in client.kv if "/arrived/" in k]
        self._run_barrier(monkeypatch, client, my_rank=1)
        assert all(k in client.kv for k in first_keys)  # root's job, not ours


def test_call_site_tag_includes_parent_dir():
    """A bare basename collides across packages (every repo has a train.py);
    the tag carries the last TWO path components."""
    tag = runtime.broadcast_object.__globals__["_call_site_tag"]()
    assert tag.count("/") == 1  # exactly dir/file.py:lineno
    assert tag.startswith("tests/test_runtime.py:")


class TestInitLadder:
    """init_auto's detection priority (reference util/distributed.py:227-244):
    explicit env coordinator > TPU-pod metadata > Slurm > MPI > single —
    initializers stubbed so no network or cluster is needed."""

    def _stub(self, monkeypatch, chosen):
        for name in ("init_from_env", "init_tpu_pod", "init_slurm", "init_mpi", "init_single"):
            monkeypatch.setattr(runtime, name, lambda n=name, **kw: chosen.append(n))
        monkeypatch.setattr(runtime._info, "initialized", False)

    def test_tpu_pod_detection_requires_multiple_hosts(self, monkeypatch):
        monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
        assert not runtime.has_tpu_pod_env()
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0")
        assert not runtime.has_tpu_pod_env()  # single host: plain init
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1,host2,host3")
        assert runtime.has_tpu_pod_env()

    def test_explicit_coordinator_beats_tpu_pod(self, monkeypatch):
        chosen = []
        self._stub(monkeypatch, chosen)
        monkeypatch.setenv("DMLCLOUD_TPU_COORDINATOR", "h:1")
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        runtime.init_auto()
        assert chosen == ["init_from_env"]

    def test_tpu_pod_beats_slurm(self, monkeypatch):
        chosen = []
        self._stub(monkeypatch, chosen)
        monkeypatch.delenv("DMLCLOUD_TPU_COORDINATOR", raising=False)
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        monkeypatch.setenv("SLURM_PROCID", "0")
        runtime.init_auto()
        assert chosen == ["init_tpu_pod"]

    def test_fallback_is_single(self, monkeypatch):
        chosen = []
        self._stub(monkeypatch, chosen)
        for var in ("DMLCLOUD_TPU_COORDINATOR", "JAX_COORDINATOR_ADDRESS",
                    "TPU_WORKER_HOSTNAMES", "SLURM_PROCID"):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setattr(runtime, "has_mpi", lambda: False)
        runtime.init_auto()
        assert chosen == ["init_single"]
