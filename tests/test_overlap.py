"""Overlap engine: deferred metrics are numerically identical to the eager
path, nothing inside the step loop reads a device value when deferred is on,
host stall is accounted, the NaN guard fires at log boundaries, and the
prefetch plumbing feeds identical batches."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import dmlcloud_tpu as dml


class _ToyStage(dml.TrainValStage):
    """Deterministic linear regression; flags overridable per test."""

    def __init__(self, deferred=True, prefetch=2, log_every_n=50, guard=True, n_batches=8):
        super().__init__()
        self._deferred = deferred
        self._prefetch = prefetch
        self._log_every = log_every_n
        self._guard = guard
        self._n_batches = n_batches

    def deferred_metrics(self):
        return self._deferred

    def prefetch_depth(self):
        return self._prefetch

    def log_every(self):
        return self._log_every

    def nan_guard(self):
        return self._guard

    def pre_stage(self):
        rng = np.random.RandomState(7)
        w_true = rng.randn(4, 1).astype(np.float32)
        xs = rng.randn(self._n_batches, 16, 4).astype(np.float32)
        batches = [{"x": x, "y": x @ w_true} for x in xs]
        self.pipeline.register_model(
            "linear",
            apply_fn=lambda p, x: x @ p["w"],
            params={"w": jnp.zeros((4, 1))},
            verbose=False,
        )
        self.pipeline.register_optimizer("sgd", optax.sgd(0.05, momentum=0.9))
        self.pipeline.register_dataset("train", batches, verbose=False)

    def step(self, state, batch):
        pred = state.apply_fn(state.params, batch["x"])
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, {"abs_err": jnp.mean(jnp.abs(pred - batch["y"]))}

    def val_epoch(self):
        pass


def _run(stage, max_epochs=3):
    pipeline = dml.TrainingPipeline(name="overlap")
    pipeline.append_stage(stage, max_epochs=max_epochs, name="TrainValStage")
    pipeline.run()
    return pipeline


def test_deferred_metrics_match_eager_path(single_runtime):
    """Epoch-end reduced values must be identical whether per-step metrics
    stayed on device (deferred) or were fetched every step (eager)."""
    p_def = _run(_ToyStage(deferred=True))
    p_eag = _run(_ToyStage(deferred=False))
    for name in ("train/loss", "train/abs_err", "misc/total_train_batches"):
        a = [float(v) for v in p_def.tracker[name]]
        b = [float(v) for v in p_eag.tracker[name]]
        np.testing.assert_allclose(a, b, rtol=0, atol=0, err_msg=name)


def test_no_device_readback_in_step_loop_when_deferred(single_runtime, monkeypatch):
    """With deferred_metrics on, no jax.device_get (and no .item()) may run
    while the per-batch body executes — syncs belong to the boundaries."""
    stage = _ToyStage(deferred=True)
    in_loop_gets: list = []
    real_get = jax.device_get

    def counting_get(x):
        if stage._in_step_loop:
            in_loop_gets.append(x)
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)
    real_item = jax.Array.item

    def counting_item(self_arr):
        if stage._in_step_loop:
            in_loop_gets.append(self_arr)
        return real_item(self_arr)

    monkeypatch.setattr(jax.Array, "item", counting_item)
    _run(stage)
    assert in_loop_gets == []


def test_eager_path_does_sync_per_step(single_runtime, monkeypatch):
    """The bisection baseline must actually be eager — the flag has to flip
    real behavior, or A/B comparisons measure nothing."""
    stage = _ToyStage(deferred=False)
    count = [0]
    real_get = jax.device_get

    def counting_get(x):
        if stage._in_step_loop:
            count[0] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", counting_get)
    _run(stage, max_epochs=1)
    assert count[0] >= stage._n_batches  # at least one readback per step


def test_host_stall_metric_tracked(single_runtime):
    p = _run(_ToyStage())
    stalls = p.tracker["misc/host_stall_ms"]
    assert len(stalls) == 3
    assert all(float(s) >= 0.0 for s in stalls)


def test_nan_guard_fires_at_log_boundary(single_runtime):
    class NaNStage(_ToyStage):
        def step(self, state, batch):
            loss = jnp.mean((state.apply_fn(state.params, batch["x"]) - batch["y"]) ** 2)
            return loss / 0.0  # NaN from step one

    with pytest.raises(FloatingPointError, match="non-finite loss"):
        _run(NaNStage(log_every_n=4), max_epochs=1)


def test_nan_guard_disabled_does_not_raise(single_runtime):
    class NaNStage(_ToyStage):
        def step(self, state, batch):
            loss = jnp.mean((state.apply_fn(state.params, batch["x"]) - batch["y"]) ** 2)
            return loss / 0.0

    p = _run(NaNStage(log_every_n=4, guard=False), max_epochs=1)
    assert np.isnan(float(p.tracker["train/loss"][-1]))


def test_nan_guard_eager_checks_every_step(single_runtime):
    class NaNStage(_ToyStage):
        def step(self, state, batch):
            loss = jnp.mean((state.apply_fn(state.params, batch["x"]) - batch["y"]) ** 2)
            return loss / 0.0

    # eager mode needs no log boundary to catch it
    with pytest.raises(FloatingPointError, match="non-finite loss"):
        _run(NaNStage(deferred=False, log_every_n=0), max_epochs=1)


def test_prefetch_depths_equivalent(single_runtime):
    """prefetch_depth 0 / 2 and host_prefetch must all see the same batches
    in the same order — overlap must never change the computation."""

    class HostPrefetchStage(_ToyStage):
        def host_prefetch(self):
            return 2

    runs = [
        _run(_ToyStage(prefetch=0)),
        _run(_ToyStage(prefetch=2)),
        _run(HostPrefetchStage(prefetch=2)),
    ]
    losses = [[float(v) for v in p.tracker["train/loss"]] for p in runs]
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
    np.testing.assert_allclose(losses[0], losses[2], rtol=1e-6)


def test_device_prefetch_override_still_respected(single_runtime):
    """Back-compat: an old-style device_prefetch() override must keep feeding
    through prefetch_depth()'s default delegation."""

    class OldStyle(dml.TrainValStage):  # no prefetch_depth override
        def device_prefetch(self):
            return 0

    stage = OldStyle()
    assert stage.prefetch_depth() == 0
