"""MoE layer: routing, capacity, aux losses, expert parallelism over the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlcloud_tpu.models.moe import MoEConfig, MoEMLP, moe_partition_rules, total_aux_loss
from dmlcloud_tpu.parallel import mesh as mesh_lib

B, T, D = 2, 16, 8


def make_layer(**overrides):
    kwargs = dict(num_experts=4, top_k=2, hidden_dim=D, mlp_dim=16, dtype=jnp.float32)
    kwargs.update(overrides)
    cfg = MoEConfig(**kwargs)
    model = MoEMLP(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (B, T, D))
    variables = model.init(jax.random.PRNGKey(1), x)
    return model, {"params": variables["params"]}, x


class TestMoEMLP:
    @pytest.mark.slow
    def test_forward_shape_and_finite(self):
        model, params, x = make_layer()
        y = model.apply(params, x)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()

    @pytest.mark.slow
    def test_output_nonzero_with_ample_capacity(self):
        # capacity_factor high enough that no token is dropped: every token
        # got routed, so no row of the output should be exactly zero.
        model, params, x = make_layer(capacity_factor=4.0)
        y = np.asarray(model.apply(params, x)).reshape(-1, D)
        assert (np.abs(y).sum(axis=-1) > 0).all()

    def test_capacity_drops_tokens(self):
        # capacity 1 per expert: with B*T=32 tokens and 4 experts most
        # (token, choice) pairs overflow; the layer must still be finite.
        model, params, x = make_layer(capacity_factor=0.01)
        y = model.apply(params, x)
        assert np.isfinite(np.asarray(y)).all()

    def test_aux_losses_sown(self):
        model, params, x = make_layer()
        y, state = model.apply(params, x, mutable=["losses"])
        aux = total_aux_loss(state)
        assert np.isfinite(float(aux))
        assert float(aux) > 0.0

    def test_gradients_flow_to_all_param_groups(self):
        model, params, x = make_layer(capacity_factor=4.0)

        def loss_fn(p):
            y, state = model.apply(p, x, mutable=["losses"])
            return jnp.sum(y**2) + total_aux_loss(state)

        grads = jax.grad(loss_fn)(params)
        flat = jax.tree_util.tree_leaves_with_path(grads)
        assert len(flat) == 4  # router + gate/up/down
        for path, g in flat:
            assert np.abs(np.asarray(g)).sum() > 0, f"zero grad at {path}"

    def test_top1_switch_mode(self):
        model, params, x = make_layer(top_k=1)
        y = model.apply(params, x)
        assert y.shape == x.shape


class TestExpertParallel:
    def test_sharded_matches_single_device(self):
        """The same einsum formulation, experts sharded over the mesh, must be
        numerically identical to the unsharded apply."""
        model, params, x = make_layer(num_experts=8, capacity_factor=2.0)
        y_ref = model.apply(params, x)

        mesh = mesh_lib.create_mesh({"data": 2, "expert": 4})
        rules = moe_partition_rules()
        sharded_params = mesh_lib.shard_pytree(params, mesh, rules)
        x_sharded = jax.device_put(x, mesh_lib.batch_sharding(mesh))

        y = jax.jit(model.apply)(sharded_params, x_sharded)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)

    def test_partition_rules_shard_expert_dim(self):
        model, params, _ = make_layer(num_experts=8)
        mesh = mesh_lib.create_mesh({"expert": 8})
        shardings = mesh_lib.sharding_for(params, mesh, moe_partition_rules())
        flat = jax.tree_util.tree_leaves_with_path(shardings)
        expert_sharded = [s for path, s in flat if "proj" in jax.tree_util.keystr(path)]
        assert len(expert_sharded) == 3
        for s in expert_sharded:
            assert s.spec[0] == "expert"


class TestMoETransformer:
    @pytest.mark.slow
    def test_decoder_lm_with_moe(self):
        from dmlcloud_tpu.models.transformer import DecoderLM, TransformerConfig, lm_loss

        cfg = TransformerConfig(
            vocab_size=64,
            num_layers=2,
            num_heads=2,
            head_dim=8,
            hidden_dim=16,
            mlp_dim=32,
            max_seq_len=32,
            dtype=jnp.float32,
            num_experts=4,
            moe_every=2,
        )
        model = DecoderLM(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
        params = model.init(jax.random.PRNGKey(1), tokens)
        # layer_1 (every 2nd) is MoE, layer_0 dense
        assert "moe" in params["params"]["layer_1"]
        assert "mlp" in params["params"]["layer_0"]

        loss = lm_loss(model.apply(params, tokens), tokens)
        assert np.isfinite(float(loss))

        grads = jax.grad(lambda p: lm_loss(model.apply(p, tokens), tokens))(params)
        gate_g = grads["params"]["layer_1"]["moe"]["moe/gate_proj"]
        assert np.abs(np.asarray(gate_g)).sum() > 0
