"""Disk-native data plane suite (doc/data.md, "On-disk shard format"):
corpus-builder round trip (build → mmap → bit-identical tokens), format
validation and corrupt-shard rejection (the error names the file), the
async ShardReader's world-size-aware assignment + seek-based elastic
cursor, mmap-vs-in-memory equivalence through ``pack_stream``, and the
window-FFD packer's determinism/conservation/pad-reclaim contracts."""

import os
import threading

import numpy as np
import pytest

from dmlcloud_tpu.data import DataPipeline
from dmlcloud_tpu.data.store import (
    HEADER_SIZE,
    CorpusBuilder,
    ShardCorruptError,
    ShardFile,
    ShardReader,
    ShardStore,
    build_corpus,
    reader_activity,
    write_shard,
)


def _docs(n=200, seed=0, vocab=512, median=64.0, sigma=0.6, lo=4, hi=256):
    rs = np.random.RandomState(seed)
    lengths = np.clip(np.round(rs.lognormal(np.log(median), sigma, n)), lo, hi).astype(int)
    return [rs.randint(1, vocab, size=int(k)).astype(np.int32) for k in lengths]


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """One shared on-disk corpus: (directory, docs, manifest)."""
    d = tmp_path_factory.mktemp("corpus")
    docs = _docs()
    manifest = build_corpus(d, docs, shard_tokens=4096)
    return str(d), docs, manifest


class TestShardFormat:
    def test_builder_round_trip_bit_identical(self, corpus):
        d, docs, manifest = corpus
        assert len(manifest["shards"]) > 1  # the corpus actually sharded
        store = ShardStore(d)
        assert store.total_records == len(docs)
        assert store.total_tokens == sum(a.size for a in docs)
        for g, doc in enumerate(docs):
            rec = store.record(g)
            assert rec.dtype == np.int32
            assert np.array_equal(rec, doc)

    def test_records_are_zero_copy_views(self, corpus):
        d, _, _ = corpus
        store = ShardStore(d)
        rec = store.record(0)
        assert not rec.flags.owndata  # a view over the mmap, not a copy
        assert not rec.flags.writeable

    def test_verify_passes_on_intact_corpus(self, corpus):
        d, _, _ = corpus
        ShardStore(d, verify=True)  # must not raise

    def test_manifest_written(self, corpus):
        d, docs, manifest = corpus
        assert os.path.isfile(os.path.join(d, "corpus.json"))
        assert manifest["total_records"] == len(docs)
        assert manifest["version"] == 1

    def test_empty_and_missing_dir_rejected(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            ShardStore(tmp_path)  # exists but holds no shards
        with pytest.raises(FileNotFoundError):
            ShardStore(tmp_path / "nope")

    def test_locate_maps_global_to_shard(self, corpus):
        d, docs, _ = corpus
        store = ShardStore(d)
        base = 0
        for sid, shard in enumerate(store.shards):
            assert store.locate(base) == (sid, 0)
            assert store.locate(base + len(shard) - 1) == (sid, len(shard) - 1)
            base += len(shard)
        # one-past-the-end: the fully-consumed cursor
        assert store.locate(store.total_records) == (len(store.shards), 0)
        with pytest.raises(IndexError):
            store.locate(store.total_records + 1)


class TestCorruptRejection:
    def _copy_shard(self, corpus, tmp_path):
        d, _, _ = corpus
        src = os.path.join(d, sorted(n for n in os.listdir(d) if n.endswith(".dmlshard"))[0])
        dst = tmp_path / "corrupt-00000.dmlshard"
        dst.write_bytes(open(src, "rb").read())
        return str(dst)

    def test_payload_flip_fails_checksum_and_names_file(self, corpus, tmp_path):
        path = self._copy_shard(corpus, tmp_path)
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size - 3)
            f.write(b"\xa5")
        shard = ShardFile(path)  # structurally valid: open succeeds
        with pytest.raises(ShardCorruptError, match="corrupt-00000.dmlshard"):
            shard.verify()

    def test_truncation_rejected_at_open(self, corpus, tmp_path):
        path = self._copy_shard(corpus, tmp_path)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 10)
        with pytest.raises(ShardCorruptError, match="truncated"):
            ShardFile(path)

    def test_bad_magic_rejected(self, corpus, tmp_path):
        path = self._copy_shard(corpus, tmp_path)
        with open(path, "r+b") as f:
            f.write(b"NOTSHARD")
        with pytest.raises(ShardCorruptError, match="magic"):
            ShardFile(path)

    def test_future_version_rejected(self, corpus, tmp_path):
        path = self._copy_shard(corpus, tmp_path)
        with open(path, "r+b") as f:
            f.seek(8)
            f.write((99).to_bytes(4, "little"))
        with pytest.raises(ShardCorruptError, match="version 99"):
            ShardFile(path)

    def test_header_smaller_than_minimum(self, tmp_path):
        p = tmp_path / "tiny.dmlshard"
        p.write_bytes(b"DMLSHRD1")
        with pytest.raises(ShardCorruptError, match=str(HEADER_SIZE)):
            ShardFile(p)


class TestShardReader:
    def test_single_rank_yields_corpus_order(self, corpus):
        d, docs, _ = corpus
        reader = ShardReader(d, rank=0, world_size=1, read_ahead=16)
        got = list(reader)
        assert len(got) == len(reader) == len(docs)
        assert all(np.array_equal(a, b) for a, b in zip(got, docs))

    def test_record_strided_assignment_partitions_corpus(self, corpus):
        d, docs, _ = corpus
        for ws in (2, 3, 4):
            per_rank = [list(ShardReader(d, rank=r, world_size=ws)) for r in range(ws)]
            assert sum(len(p) for p in per_rank) == len(docs)
            for r, part in enumerate(per_rank):
                assert all(np.array_equal(a, docs[r + i * ws]) for i, a in enumerate(part))

    def test_reader_runs_on_background_thread(self, corpus):
        d, _, _ = corpus
        before = reader_activity()
        it = iter(ShardReader(d, rank=0, world_size=1, read_ahead=8))
        next(it)
        assert reader_activity() > before  # the activity counter advanced
        names = [t.name for t in threading.enumerate()]
        assert any(n == "dml-shard-reader" for n in names)
        it.close()

    def test_state_dict_carries_disk_location(self, corpus):
        d, docs, _ = corpus
        reader = ShardReader(d, rank=0, world_size=2)
        it = iter(reader)
        for _ in range(7):
            next(it)
        st = reader.state_dict()
        assert st["kind"] == "shards"
        assert st["global_offset"] == 14
        assert st["world_size"] == 2
        sid, off = reader.store.locate(14)
        assert (st["shard_id"], st["record_offset"]) == (sid, off)
        it.close()

    @pytest.mark.parametrize("old_ws,new_ws", [(4, 2), (2, 4), (2, 1), (1, 2)])
    def test_resume_across_world_sizes_zero_replay(self, corpus, old_ws, new_ws):
        """Consume a prefix on old_ws, save, resume on new_ws: the union of
        the two phases covers every record exactly once."""
        d, docs, _ = corpus
        # per-rank records consumed before the "preemption"; chosen so
        # k * old_ws divides every new_ws — the exact-resume precondition
        k = 12
        seen = []
        readers = [ShardReader(d, rank=r, world_size=old_ws) for r in range(old_ws)]
        iters = [iter(r) for r in readers]
        for _ in range(k):
            for it in iters:
                seen.append(next(it))
        state = readers[0].state_dict()
        assert state["global_offset"] == k * old_ws
        for it in iters:
            it.close()
        for r in range(new_ws):
            reader = ShardReader(d, rank=r, world_size=new_ws)
            reader.load_state_dict(state)
            seen.extend(reader)
        assert len(seen) == len(docs)  # 0 replayed, 0 skipped
        counts: dict = {}
        for rec in seen:
            key = rec.tobytes()
            counts[key] = counts.get(key, 0) + 1
        expected: dict = {}
        for doc in docs:
            key = doc.tobytes()
            expected[key] = expected.get(key, 0) + 1
        assert counts == expected

    def test_indivisible_offset_warns_and_rounds_down(self, corpus, caplog):
        d, _, _ = corpus
        reader = ShardReader(d, rank=0, world_size=3)
        state = {"v": 1, "kind": "shards", "epoch": None, "global_offset": 7,
                 "world_size": 7, "shard_id": 0, "record_offset": 7}
        import logging

        with caplog.at_level(logging.WARNING, logger="dmlcloud_tpu"):
            reader.load_state_dict(state)
        assert any("not divisible" in r.message for r in caplog.records)
        assert reader._shard_resume == 2  # 7 // 3

    def test_plain_state_degrades_to_replay_skip(self, corpus):
        d, docs, _ = corpus
        reader = ShardReader(d, rank=0, world_size=1)
        reader.load_state_dict({"v": 1, "epoch": None, "global_offset": 5, "world_size": 1})
        got = list(reader)
        assert len(got) == len(docs) - 5
        assert np.array_equal(got[0], docs[5])

    def test_state_after_full_consumption(self, corpus):
        d, docs, _ = corpus
        reader = ShardReader(d, rank=0, world_size=1)
        list(reader)
        st = reader.state_dict()
        assert st["global_offset"] == len(docs)
        assert st["shard_id"] == len(reader.store.shards)
        assert st["record_offset"] == 0

    def test_ctor_validation(self, corpus):
        d, _, _ = corpus
        with pytest.raises(ValueError):
            ShardReader(d, buffers=0)
        with pytest.raises(ValueError):
            ShardReader(d, read_ahead=0)


class TestPackEquivalence:
    def test_mmap_reader_equals_in_memory_through_pack_stream(self, corpus):
        d, docs, _ = corpus
        mem = DataPipeline.from_source(docs).pack_stream(256, chunk_docs=64)
        dsk = ShardReader(d, rank=0, world_size=1).pack_stream(256, chunk_docs=64)
        rows_m, rows_d = list(mem), list(dsk)
        assert len(rows_m) == len(rows_d)
        for a, b in zip(rows_m, rows_d):
            assert np.array_equal(a["tokens"], b["tokens"])
            assert np.array_equal(a["segment_ids"], b["segment_ids"])

    def test_mmap_reader_equals_in_memory_through_ffd(self, corpus):
        d, docs, _ = corpus
        mem = DataPipeline.from_source(docs).pack_stream(256, pack_window=64)
        dsk = ShardReader(d, rank=0, world_size=1).pack_stream(256, pack_window=64)
        for a, b in zip(list(mem), list(dsk)):
            assert np.array_equal(a["tokens"], b["tokens"])
            assert np.array_equal(a["segment_ids"], b["segment_ids"])


class TestFFDPacking:
    def test_determinism_lock(self):
        """Bit-identical rows across repeated runs — the receipt's
        reproducibility contract."""
        docs = _docs(300, seed=3)
        runs = []
        for _ in range(2):
            p = DataPipeline.from_source(docs).pack_stream(256, pack_window=128)
            runs.append(list(p))
        assert len(runs[0]) == len(runs[1])
        for a, b in zip(*runs):
            assert np.array_equal(a["tokens"], b["tokens"])
            assert np.array_equal(a["segment_ids"], b["segment_ids"])

    def test_conserves_tokens_and_segments(self):
        docs = _docs(250, seed=5)
        p = DataPipeline.from_source(docs).pack_stream(256, pack_window=64)
        rows = list(p)
        real = np.concatenate([r["tokens"][r["segment_ids"] > 0] for r in rows])
        assert sorted(real.tolist()) == sorted(np.concatenate(docs).tolist())
        # every row's segment ids are 1..k contiguous, padding strictly 0
        for r in rows:
            segs = r["segment_ids"]
            present = sorted(set(segs.tolist()) - {0})
            assert present == list(range(1, len(present) + 1))
            assert np.all(r["tokens"][segs == 0] == 0)

    def test_reclaims_greedy_padding(self):
        """The tentpole number: window FFD beats the chunked greedy packer
        on the pinned lognormal corpus and lands under the 0.10 target."""
        docs = _docs(600, seed=0)
        greedy = DataPipeline.from_source(docs).pack_stream(256, chunk_docs=192)
        ffd = DataPipeline.from_source(docs).pack_stream(256, pack_window=512)
        list(greedy), list(ffd)
        assert ffd.pack_stats.pad_fraction < greedy.pack_stats.pad_fraction
        assert ffd.pack_stats.pad_fraction <= 0.10

    def test_long_docs_split_into_full_rows(self):
        rs = np.random.RandomState(1)
        docs = [rs.randint(1, 99, size=700).astype(np.int32), np.arange(1, 20, dtype=np.int32)]
        p = DataPipeline.from_source(docs).pack_stream(256, pack_window=8)
        rows = list(p)
        real = np.concatenate([r["tokens"][r["segment_ids"] > 0] for r in rows])
        assert real.size == 700 + 19  # split_long places every token
        # the two full 256-slot pieces of the long doc are single-segment rows
        full = [r for r in rows if np.all(r["segment_ids"] == 1)]
        assert len(full) >= 2

    def test_split_long_false_truncates(self):
        docs = [np.arange(1, 400, dtype=np.int32)]
        p = DataPipeline.from_source(docs).pack_stream(256, pack_window=4, split_long=False)
        rows = list(p)
        assert len(rows) == 1
        assert np.array_equal(rows[0]["tokens"], np.arange(1, 257, dtype=np.int32))

    def test_open_bin_cap_bounds_memory(self):
        """More unpackable-together docs than the bin cap: rows still emit
        (eviction) and every token still lands exactly once."""
        docs = [np.full(200, i + 1, np.int32) for i in range(100)]  # none pair up
        p = DataPipeline.from_source(docs).pack_stream(256, pack_window=4)
        rows = list(p)
        real = np.concatenate([r["tokens"][r["segment_ids"] > 0] for r in rows])
        assert real.size == 200 * 100

    def test_pack_window_zero_is_greedy_mode(self):
        docs = _docs(100, seed=2)
        a = DataPipeline.from_source(docs).pack_stream(256, chunk_docs=64)
        b = DataPipeline.from_source(docs).pack_stream(256, chunk_docs=64, pack_window=0)
        for ra, rb in zip(list(a), list(b)):
            assert np.array_equal(ra["tokens"], rb["tokens"])

    def test_validation(self):
        docs = _docs(10)
        with pytest.raises(ValueError):
            DataPipeline.from_source(docs).pack_stream(256, pack_window=-1)


class TestBuilderEdgeCases:
    def test_write_shard_empty(self, tmp_path):
        info = write_shard(tmp_path / "empty.dmlshard", [])
        assert info["records"] == 0 and info["tokens"] == 0
        shard = ShardFile(tmp_path / "empty.dmlshard")
        assert len(shard) == 0
        shard.verify()

    def test_builder_rolls_by_token_budget(self, tmp_path):
        b = CorpusBuilder(tmp_path, shard_tokens=100)
        for _ in range(10):
            b.add(np.ones(40, np.int32))
        manifest = b.finalize()
        assert len(manifest["shards"]) > 1
        assert all(s["tokens"] <= 120 for s in manifest["shards"])
        with pytest.raises(RuntimeError):
            b.add(np.ones(3, np.int32))

    def test_reader_activity_counter_is_module_level(self, corpus):
        d, _, _ = corpus
        a = reader_activity()
        list(ShardReader(d, rank=0, world_size=1, read_ahead=32))
        assert reader_activity() > a
