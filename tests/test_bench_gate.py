"""``bench.py --gate`` — the perf regression gate over kernel receipts.

The gate compares the flat ``gate`` section (kernel speedups, accept rate)
plus the goodput fraction of the current run against the last committed
``BENCH_kernels_*.json`` receipt: PASS when nothing dropped more than the
tolerance, FAIL on a significant drop OR a metric that silently vanished
(the r05 all-null receipt must never slip through again).
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _GATE_TOLERANCE, _gate_metrics, gate_main, run_gate

RECEIPT = {
    "flash_attn": {"fwd_speedup_vs_unfused": 1.6},
    "gate": {
        "flash_fwd_speedup_vs_unfused": 1.6,
        "flash_fwdbwd_speedup_vs_unfused": 1.7,
        "spec_decode_speedup_vs_plain": 1.5,
        "spec_decode_accept_rate": 0.9,
        "int8_decode_speedup": 1.25,
    },
}


def _write(tmp_path, name, data):
    p = tmp_path / name
    p.write_text(json.dumps(data))
    return str(p)


def test_gate_passes_against_itself(tmp_path, capsys):
    base = _write(tmp_path, "BENCH_kernels_base.json", RECEIPT)
    assert run_gate(base, current=dict(RECEIPT)) == 0
    assert "PASS" in capsys.readouterr().out


def test_gate_passes_within_tolerance(tmp_path):
    current = json.loads(json.dumps(RECEIPT))
    for k in current["gate"]:
        current["gate"][k] *= 1 - _GATE_TOLERANCE * 0.5  # half the allowed drop
    base = _write(tmp_path, "BENCH_kernels_base.json", RECEIPT)
    assert run_gate(base, current=current) == 0


def test_gate_fails_against_doctored_regression(tmp_path, capsys):
    doctored = json.loads(json.dumps(RECEIPT))
    doctored["gate"]["flash_fwdbwd_speedup_vs_unfused"] = 0.48  # the old losing kernel
    doctored["gate"]["spec_decode_accept_rate"] = 0.0
    base = _write(tmp_path, "BENCH_kernels_base.json", RECEIPT)
    cur = _write(tmp_path, "doctored.json", doctored)
    assert run_gate(base, current=cur) == 1  # path form, like the CLI
    out = capsys.readouterr().out
    assert "FAIL" in out and "flash_fwdbwd_speedup_vs_unfused" in out
    assert "spec_decode_accept_rate" in out


def test_gate_fails_on_silently_missing_metric(tmp_path, capsys):
    """An all-null / truncated current receipt is a FAILURE, not a pass —
    exactly how the r05 receipt went dark without anyone noticing."""
    current = {"gate": {k: v for k, v in RECEIPT["gate"].items() if "int8" not in k}}
    base = _write(tmp_path, "BENCH_kernels_base.json", RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_gate_improvements_always_pass(tmp_path):
    current = json.loads(json.dumps(RECEIPT))
    for k in current["gate"]:
        current["gate"][k] *= 2.0
    base = _write(tmp_path, "BENCH_kernels_base.json", RECEIPT)
    assert run_gate(base, current=current) == 0


def test_gate_compares_goodput_when_present(tmp_path):
    base_r = json.loads(json.dumps(RECEIPT))
    base_r["goodput_frac"] = 0.8
    cur = json.loads(json.dumps(base_r))
    cur["goodput_frac"] = 0.5  # productive fraction collapsed
    base = _write(tmp_path, "base.json", base_r)
    assert run_gate(base, current=cur) == 1
    cur["goodput_frac"] = 0.78
    assert run_gate(base, current=cur) == 0


def test_gate_metrics_reads_driver_wrapped_receipts():
    """Full bench.py receipts are committed driver-wrapped ({"parsed": ...});
    the goodput key must be found in either shape."""
    wrapped = {"parsed": {"goodput_frac": 0.7}, "gate": {"x": 1.0}}
    assert _gate_metrics(wrapped) == {"x": 1.0, "goodput_frac": 0.7}
    bare = {"goodput_frac": 0.7}
    assert _gate_metrics(bare) == {"goodput_frac": 0.7}


def test_gate_main_flags(tmp_path):
    doctored = json.loads(json.dumps(RECEIPT))
    doctored["gate"]["int8_decode_speedup"] = 0.5
    base = _write(tmp_path, "base.json", RECEIPT)
    cur = _write(tmp_path, "cur.json", doctored)
    assert gate_main(["--gate", "--baseline", base, "--current", cur]) == 1
    # a huge tolerance waves the same drop through
    assert gate_main(["--gate", "--baseline", base, "--current", cur, "--tolerance", "0.9"]) == 0


def test_gate_no_baseline_is_an_error(tmp_path):
    with pytest.raises(FileNotFoundError):
        run_gate(str(tmp_path / "missing.json"), current={})
    # a baseline with no comparable metrics cannot vouch for anything
    empty = _write(tmp_path, "empty.json", {"gate": {}})
    assert run_gate(empty, current=dict(RECEIPT)) == 2


def test_committed_receipt_satisfies_the_gate():
    """The committed PR 6 receipt must pass its own gate — and its gate
    section must show the three reclaimed kernels above their floors."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_kernels_pr06.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    gate = json.load(open(path))["gate"]
    assert gate["flash_fwd_speedup_vs_unfused"] >= 1.0
    assert gate["flash_fwdbwd_speedup_vs_unfused"] >= 1.0
    assert gate["spec_decode_speedup_vs_plain"] >= 1.3
    assert gate["spec_decode_accept_rate"] >= 0.6
    assert gate["int8_decode_speedup"] >= 1.2


# ----------------------------------------------------------- elastic suite

ELASTIC_RECEIPT = {
    "steps_replayed": 0,
    "gate": {
        "elastic_exact_resume": 1.0,
        "elastic_save_on_preempt_latency_s": 0.02,
        "elastic_time_to_resume_s": 0.03,
    },
}


def test_elastic_gate_passes_against_itself(tmp_path):
    base = _write(tmp_path, "BENCH_elastic_base.json", ELASTIC_RECEIPT)
    assert run_gate(base, current=dict(ELASTIC_RECEIPT)) == 0


def test_elastic_latencies_are_lower_is_better(tmp_path, capsys):
    """A latency that GROWS past the (wide) latency tolerance fails; one
    that merely shrinks — a speedup — always passes."""
    slow = json.loads(json.dumps(ELASTIC_RECEIPT))
    slow["gate"]["elastic_time_to_resume_s"] = 0.03 * 2.5  # > 2x baseline
    base = _write(tmp_path, "BENCH_elastic_base.json", ELASTIC_RECEIPT)
    assert run_gate(base, current=slow) == 1
    assert "elastic_time_to_resume_s" in capsys.readouterr().out
    fast = json.loads(json.dumps(ELASTIC_RECEIPT))
    fast["gate"]["elastic_save_on_preempt_latency_s"] = 0.001
    fast["gate"]["elastic_time_to_resume_s"] = 0.001
    assert run_gate(base, current=fast) == 0


def test_elastic_latency_noise_within_2x_passes(tmp_path):
    noisy = json.loads(json.dumps(ELASTIC_RECEIPT))
    noisy["gate"]["elastic_save_on_preempt_latency_s"] = 0.02 * 1.8
    noisy["gate"]["elastic_time_to_resume_s"] = 0.03 * 1.8
    base = _write(tmp_path, "BENCH_elastic_base.json", ELASTIC_RECEIPT)
    assert run_gate(base, current=noisy) == 0


def test_elastic_replayed_step_fails_exact_resume(tmp_path, capsys):
    """A drill that replayed (or skipped) even one optimizer step reports
    elastic_exact_resume 0.0 — a 100% drop, always a FAIL."""
    replayed = json.loads(json.dumps(ELASTIC_RECEIPT))
    replayed["steps_replayed"] = 2
    replayed["gate"]["elastic_exact_resume"] = 0.0
    base = _write(tmp_path, "BENCH_elastic_base.json", ELASTIC_RECEIPT)
    assert run_gate(base, current=replayed) == 1
    assert "elastic_exact_resume" in capsys.readouterr().out


def test_elastic_missing_metric_fails(tmp_path, capsys):
    """Same semantics as the kernel gate: a metric the baseline carries must
    be present — a drill that silently stopped reporting latency FAILS."""
    current = {"gate": {"elastic_exact_resume": 1.0}}
    base = _write(tmp_path, "BENCH_elastic_base.json", ELASTIC_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_gate_main_elastic_suite_with_explicit_files(tmp_path):
    base = _write(tmp_path, "BENCH_elastic_base.json", ELASTIC_RECEIPT)
    cur = _write(tmp_path, "cur.json", ELASTIC_RECEIPT)
    assert gate_main(["--gate", "--suite", "elastic", "--baseline", base, "--current", cur]) == 0
    assert gate_main(["--gate", "--suite", "nope"]) == 2


# ------------------------------------------------------------- serve suite

SERVE_RECEIPT = {
    "value_source": "cpu_smoke",
    "token_identical_to_serial": True,
    "gate": {
        "serve_tokens_per_sec_speedup": 3.0,
        "serve_engine_tokens_per_sec": 300.0,
        "serve_p99_ttft_s": 1.5,
    },
}


def test_serve_gate_passes_against_itself(tmp_path):
    base = _write(tmp_path, "BENCH_serve_base.json", SERVE_RECEIPT)
    assert run_gate(base, current=dict(SERVE_RECEIPT)) == 0


def test_serve_gate_fails_against_doctored_regression(tmp_path, capsys):
    """An engine that stopped beating serial generate (speedup collapses
    below the committed number) FAILS the gate."""
    doctored = json.loads(json.dumps(SERVE_RECEIPT))
    doctored["gate"]["serve_tokens_per_sec_speedup"] = 0.9  # engine lost its win
    doctored["gate"]["serve_engine_tokens_per_sec"] = 90.0
    base = _write(tmp_path, "BENCH_serve_base.json", SERVE_RECEIPT)
    cur = _write(tmp_path, "doctored.json", doctored)
    assert run_gate(base, current=cur) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out and "serve_tokens_per_sec_speedup" in out


def test_serve_p99_ttft_is_lower_is_better(tmp_path, capsys):
    """TTFT is a latency: growth past the wide latency tolerance fails,
    shrinking (an improvement) always passes."""
    slow = json.loads(json.dumps(SERVE_RECEIPT))
    slow["gate"]["serve_p99_ttft_s"] = 1.5 * 2.5  # > 2x baseline
    base = _write(tmp_path, "BENCH_serve_base.json", SERVE_RECEIPT)
    assert run_gate(base, current=slow) == 1
    assert "serve_p99_ttft_s" in capsys.readouterr().out
    fast = json.loads(json.dumps(SERVE_RECEIPT))
    fast["gate"]["serve_p99_ttft_s"] = 0.1
    assert run_gate(base, current=fast) == 0


def test_serve_missing_metric_fails(tmp_path, capsys):
    """PR-6 semantics: a serve metric that silently vanishes is a FAIL."""
    current = {"gate": {"serve_tokens_per_sec_speedup": 3.0}}
    base = _write(tmp_path, "BENCH_serve_base.json", SERVE_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_gate_main_serve_suite_with_explicit_files(tmp_path):
    base = _write(tmp_path, "BENCH_serve_base.json", SERVE_RECEIPT)
    cur = _write(tmp_path, "cur.json", SERVE_RECEIPT)
    assert gate_main(["--gate", "--suite", "serve", "--baseline", base, "--current", cur]) == 0


def test_committed_serve_receipt_satisfies_the_gate():
    """The committed PR 8 receipt must pass its own gate, beat serial
    generate by the acceptance floor (1.5x tokens/s), report p99 TTFT,
    stay inside its TraceGuard signature budget, decode token-identically
    to serial generate, and be honest about where it ran."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_serve_pr08.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    assert receipt["gate"]["serve_tokens_per_sec_speedup"] >= 1.5
    assert receipt["gate"]["serve_p99_ttft_s"] > 0
    assert receipt["serial"]["p99_ttft_s"] > 0
    assert receipt["token_identical_to_serial"] is True
    assert receipt["value_source"] == "cpu_smoke"
    eng = receipt["engine"]
    assert eng["completed"] == receipt["config"]["n_requests"]
    assert eng["compiled_signatures"] <= eng["max_signatures"]


# ------------------------------------------------ serve suite: spec decode

SERVE_SPEC_RECEIPT = {
    "value_source": "cpu_smoke",
    "gate": {
        "serve_tokens_per_sec_speedup": 3.0,
        "serve_engine_tokens_per_sec": 300.0,
        "serve_p99_ttft_s": 1.5,
        "serve_spec_speedup_vs_engine": 1.6,
        "serve_spec_accept_rate": 0.9,
        "serve_spec_tokens_per_sec": 480.0,
        "serve_spec_p99_ttft_s": 1.8,
        "serve_spec_token_identical": 1,
        "serve_spec_zero_recompiles": 1,
    },
}


def test_serve_spec_accept_rate_regression_fails(tmp_path, capsys):
    """A collapsing accept rate (the r01-r05 0.0 failure mode) is a
    regression like any other ratio: dropping past tolerance FAILS."""
    doctored = json.loads(json.dumps(SERVE_SPEC_RECEIPT))
    doctored["gate"]["serve_spec_accept_rate"] = 0.2
    doctored["gate"]["serve_spec_speedup_vs_engine"] = 1.5
    base = _write(tmp_path, "BENCH_serve_spec_base.json", SERVE_SPEC_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "serve_spec_accept_rate" in capsys.readouterr().out


def test_serve_spec_speedup_regression_fails(tmp_path, capsys):
    """Speculation that stops composing with the engine (speedup back to
    ~1x) FAILS against the committed receipt."""
    doctored = json.loads(json.dumps(SERVE_SPEC_RECEIPT))
    doctored["gate"]["serve_spec_speedup_vs_engine"] = 1.0
    doctored["gate"]["serve_spec_tokens_per_sec"] = 300.0
    base = _write(tmp_path, "BENCH_serve_spec_base.json", SERVE_SPEC_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "serve_spec_speedup_vs_engine" in capsys.readouterr().out


def test_serve_spec_identity_and_recompiles_are_pass_fail(tmp_path, capsys):
    """Token identity and the zero-mid-run-recompile contract ride the
    gate as 1/0 ints: flipping either to 0 is a 100% drop — FAIL."""
    for key in ("serve_spec_token_identical", "serve_spec_zero_recompiles"):
        doctored = json.loads(json.dumps(SERVE_SPEC_RECEIPT))
        doctored["gate"][key] = 0
        base = _write(tmp_path, f"BENCH_serve_{key}.json", SERVE_SPEC_RECEIPT)
        assert run_gate(base, current=doctored) == 1
        assert key in capsys.readouterr().out


def test_serve_spec_missing_metric_fails(tmp_path, capsys):
    """A spec metric that silently vanishes from the current run (e.g. the
    spec arm stopped running at all) is a FAIL, not a pass."""
    current = json.loads(json.dumps(SERVE_SPEC_RECEIPT))
    del current["gate"]["serve_spec_accept_rate"]
    base = _write(tmp_path, "BENCH_serve_spec_base.json", SERVE_SPEC_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_serve_spec_p99_ttft_is_lower_is_better(tmp_path):
    fast = json.loads(json.dumps(SERVE_SPEC_RECEIPT))
    fast["gate"]["serve_spec_p99_ttft_s"] = 0.2  # improvement: passes
    base = _write(tmp_path, "BENCH_serve_spec_base.json", SERVE_SPEC_RECEIPT)
    assert run_gate(base, current=fast) == 0
    slow = json.loads(json.dumps(SERVE_SPEC_RECEIPT))
    slow["gate"]["serve_spec_p99_ttft_s"] = 1.8 * 2.5  # > 2x: regression
    assert run_gate(base, current=slow) == 1


def test_committed_serve_spec_receipt_satisfies_the_gate():
    """The committed PR 10 receipt must pass its own gate and meet the
    acceptance floors: spec engine >= 1.4x the non-spec engine's tokens/s
    at accept rate >= 0.8, greedy output token-identical to serial
    generate, zero mid-run recompiles inside the TraceGuard budget."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_serve_spec_pr10.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    assert gate["serve_spec_speedup_vs_engine"] >= 1.4
    assert gate["serve_spec_accept_rate"] >= 0.8
    assert gate["serve_spec_token_identical"] == 1
    assert gate["serve_spec_zero_recompiles"] == 1
    spec = receipt["spec"]
    assert spec["token_identical_to_serial"] is True
    assert spec["mid_run_recompiles"] == 0
    eng = spec["spec_engine"]
    assert eng["compiled_signatures"] <= eng["max_signatures"]
    assert eng["completed"] == spec["config"]["n_requests"]
    assert eng["accept_rate"] >= 0.8
    # the old serve keys must still be present — one receipt carries both
    for key in ("serve_tokens_per_sec_speedup", "serve_p99_ttft_s"):
        assert key in gate


# ------------------------------------------------ serve suite: prefix cache

SERVE_PREFIX_RECEIPT = {
    "value_source": "cpu_smoke",
    "gate": {
        "serve_tokens_per_sec_speedup": 3.0,
        "serve_engine_tokens_per_sec": 300.0,
        "serve_p99_ttft_s": 1.5,
        "serve_prefix_warm_ttft_s": 0.1,
        "serve_prefix_hit_rate": 0.8,
        "serve_prefix_prefill_tokens_saved_frac": 0.7,
        "serve_prefix_token_identical": 1,
        "serve_prefix_zero_recompiles": 1,
    },
}


def test_serve_prefix_warm_ttft_is_lower_is_better(tmp_path, capsys):
    """The warm-template TTFT is the tentpole's headline latency: growth
    past the wide latency tolerance (the cache silently stopped hitting)
    FAILS; shrinking always passes."""
    slow = json.loads(json.dumps(SERVE_PREFIX_RECEIPT))
    slow["gate"]["serve_prefix_warm_ttft_s"] = 0.1 * 2.5  # > 2x baseline
    base = _write(tmp_path, "BENCH_serve_prefix_base.json", SERVE_PREFIX_RECEIPT)
    assert run_gate(base, current=slow) == 1
    assert "serve_prefix_warm_ttft_s" in capsys.readouterr().out
    fast = json.loads(json.dumps(SERVE_PREFIX_RECEIPT))
    fast["gate"]["serve_prefix_warm_ttft_s"] = 0.01
    assert run_gate(base, current=fast) == 0


def test_serve_prefix_hit_rate_regression_fails(tmp_path, capsys):
    """A collapsed hit rate (the radix tree stopped matching — e.g. a
    content-address change orphaned every cached block) is a regression
    like any ratio: dropping past tolerance FAILS."""
    doctored = json.loads(json.dumps(SERVE_PREFIX_RECEIPT))
    doctored["gate"]["serve_prefix_hit_rate"] = 0.1
    doctored["gate"]["serve_prefix_prefill_tokens_saved_frac"] = 0.05
    base = _write(tmp_path, "BENCH_serve_prefix_base.json", SERVE_PREFIX_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    out = capsys.readouterr().out
    assert "serve_prefix_hit_rate" in out
    assert "serve_prefix_prefill_tokens_saved_frac" in out


def test_serve_prefix_identity_and_recompiles_are_pass_fail(tmp_path, capsys):
    """Token identity to the uncached engine and the zero-recompile
    contract ride the gate as 1/0 ints: flipping either is a 100% drop."""
    for key in ("serve_prefix_token_identical", "serve_prefix_zero_recompiles"):
        doctored = json.loads(json.dumps(SERVE_PREFIX_RECEIPT))
        doctored["gate"][key] = 0
        base = _write(tmp_path, f"BENCH_serve_{key}.json", SERVE_PREFIX_RECEIPT)
        assert run_gate(base, current=doctored) == 1
        assert key in capsys.readouterr().out


def test_serve_prefix_missing_metric_fails(tmp_path, capsys):
    """PR-6 semantics: a prefix metric that silently vanishes from the
    current run (the prefix arm stopped running) is a FAIL, not a pass."""
    current = json.loads(json.dumps(SERVE_PREFIX_RECEIPT))
    del current["gate"]["serve_prefix_warm_ttft_s"]
    base = _write(tmp_path, "BENCH_serve_prefix_base.json", SERVE_PREFIX_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_gate_main_serve_suite_merges_every_committed_receipt(tmp_path, monkeypatch):
    """Without --baseline, the serve suite folds EVERY committed
    BENCH_serve_*.json into one merged baseline, each key at its most
    recently committed value — the pr11 receipt's prefix keys stay
    enforced (missing = FAIL) while an older receipt's stale absolute
    numbers do not resurrect as floors."""
    import bench as bench_mod

    old = {"gate": {"serve_p99_ttft_s": 1.5, "serve_tokens_per_sec_speedup": 3.0}}
    new = {"gate": {"serve_tokens_per_sec_speedup": 2.0, "serve_prefix_hit_rate": 0.8}}
    _write(tmp_path, "BENCH_serve_a.json", old)
    _write(tmp_path, "BENCH_serve_b_prefix.json", new)
    monkeypatch.setattr(
        bench_mod.os.path, "dirname", lambda p, _real=bench_mod.os.path.dirname: str(tmp_path)
    )
    # current matches the NEWER speedup (2.0, a >15% drop from the stale
    # 3.0): passes, because the later receipt's value won the merge
    both = {"gate": {"serve_p99_ttft_s": 1.5, "serve_tokens_per_sec_speedup": 2.0,
                     "serve_prefix_hit_rate": 0.8}}
    cur = _write(tmp_path, "cur.json", both)
    assert gate_main(["--gate", "--suite", "serve", "--current", cur]) == 0
    # drop the prefix key: the merged baseline still carries it — FAIL
    partial = _write(
        tmp_path, "partial.json",
        {"gate": {"serve_p99_ttft_s": 1.5, "serve_tokens_per_sec_speedup": 2.0}},
    )
    assert gate_main(["--gate", "--suite", "serve", "--current", partial]) == 1


def test_committed_serve_prefix_receipt_satisfies_the_gate():
    """The committed PR 11 receipt must pass its own gate and meet the
    acceptance floors: warm-template p50 TTFT <= 0.25x the uncached
    engine's on the 80%-shared-template trace, token-identical to the
    uncached engine, zero mid-run recompiles, a real hit rate."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_serve_prefix_pr11.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    prefix = receipt["prefix"]
    # the ISSUE's acceptance criterion: warm p50 TTFT <= 0.25x uncached
    assert prefix["warm_ttft_ratio"] <= 0.25
    assert gate["serve_prefix_warm_ttft_s"] == prefix["warm_template_p50_ttft_s"]
    assert prefix["warm_template_p50_ttft_s"] <= 0.25 * prefix["uncached_template_p50_ttft_s"]
    assert gate["serve_prefix_hit_rate"] >= 0.7  # 80% shared minus cold misses
    assert gate["serve_prefix_prefill_tokens_saved_frac"] >= 0.5
    assert gate["serve_prefix_token_identical"] == 1
    assert gate["serve_prefix_zero_recompiles"] == 1
    assert prefix["token_identical_to_uncached"] is True
    assert prefix["mid_run_recompiles"] == 0
    eng = prefix["prefix_engine"]
    assert eng["compiled_signatures"] <= eng["max_signatures"]
    assert eng["completed"] == prefix["config"]["n_requests"]
    # one receipt carries every serve key: the older suites stay enforced
    for key in ("serve_tokens_per_sec_speedup", "serve_p99_ttft_s",
                "serve_spec_speedup_vs_engine"):
        assert key in gate


# ---------------------------------------------- serve suite: overload/chaos

SERVE_CHAOS_RECEIPT = {
    "value_source": "cpu_smoke",
    "gate": {
        "serve_tokens_per_sec_speedup": 3.0,
        "serve_engine_tokens_per_sec": 300.0,
        "serve_p99_ttft_s": 1.5,
        "serve_chaos_goodput_tokens_per_sec": 40.0,
        "serve_chaos_cold_p99_ttft_s": 1.0,
        "serve_chaos_zero_leaked_blocks": 1,
        "serve_chaos_survivor_token_identical": 1,
        "serve_chaos_all_terminal": 1,
    },
}


def test_serve_chaos_goodput_regression_fails(tmp_path, capsys):
    """Goodput under injected faults is the drill's headline throughput:
    a collapse (the engine stopped finishing ok work under fire) FAILS
    past tolerance."""
    doctored = json.loads(json.dumps(SERVE_CHAOS_RECEIPT))
    doctored["gate"]["serve_chaos_goodput_tokens_per_sec"] = 10.0
    base = _write(tmp_path, "BENCH_serve_chaos_base.json", SERVE_CHAOS_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "serve_chaos_goodput_tokens_per_sec" in capsys.readouterr().out


def test_serve_chaos_cold_ttft_is_lower_is_better(tmp_path, capsys):
    """The cold tenant's p99 TTFT under the hot-tenant burst is the
    fairness observable: growth past the wide latency tolerance (DRR
    stopped protecting the cold tenant) FAILS; shrinking always passes."""
    slow = json.loads(json.dumps(SERVE_CHAOS_RECEIPT))
    slow["gate"]["serve_chaos_cold_p99_ttft_s"] = 1.0 * 2.5  # > 2x baseline
    base = _write(tmp_path, "BENCH_serve_chaos_base.json", SERVE_CHAOS_RECEIPT)
    assert run_gate(base, current=slow) == 1
    assert "serve_chaos_cold_p99_ttft_s" in capsys.readouterr().out
    fast = json.loads(json.dumps(SERVE_CHAOS_RECEIPT))
    fast["gate"]["serve_chaos_cold_p99_ttft_s"] = 0.2
    assert run_gate(base, current=fast) == 0


def test_serve_chaos_contracts_are_pass_fail(tmp_path, capsys):
    """Zero leaked blocks, every request terminal, and survivor token
    identity ride the gate as 1/0 ints: flipping any is a 100% drop."""
    for key in (
        "serve_chaos_zero_leaked_blocks",
        "serve_chaos_survivor_token_identical",
        "serve_chaos_all_terminal",
    ):
        doctored = json.loads(json.dumps(SERVE_CHAOS_RECEIPT))
        doctored["gate"][key] = 0
        base = _write(tmp_path, f"BENCH_serve_{key}.json", SERVE_CHAOS_RECEIPT)
        assert run_gate(base, current=doctored) == 1
        assert key in capsys.readouterr().out


def test_serve_chaos_missing_metric_fails(tmp_path, capsys):
    """PR-6 semantics: a chaos metric that silently vanishes from the
    current run (the drill stopped running) is a FAIL, not a pass."""
    current = json.loads(json.dumps(SERVE_CHAOS_RECEIPT))
    del current["gate"]["serve_chaos_zero_leaked_blocks"]
    base = _write(tmp_path, "BENCH_serve_chaos_base.json", SERVE_CHAOS_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_committed_serve_chaos_receipt_satisfies_the_gate():
    """The committed PR 13 receipt must pass its own gate and meet the
    acceptance floors: positive goodput under injected faults, zero
    leaked blocks, every request terminal, survivors token-identical to
    the fault-free run — and the drill actually injected something."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_serve_chaos_pr13.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    chaos = receipt["chaos"]
    assert gate["serve_chaos_goodput_tokens_per_sec"] > 0
    assert gate["serve_chaos_zero_leaked_blocks"] == 1
    assert gate["serve_chaos_survivor_token_identical"] == 1
    assert gate["serve_chaos_all_terminal"] == 1
    assert gate["serve_chaos_cold_p99_ttft_s"] > 0
    assert chaos["leaked_blocks"] == 0
    assert chaos["survivor_token_identical"] is True
    assert chaos["all_terminal"] is True
    assert chaos["survivors_ok"] > 0
    # the drill is real: faults/cancels/sheds actually happened
    assert chaos["chaos_events"] > 0
    assert sum(
        chaos["statuses"].get(k, 0) for k in ("shed", "cancelled", "error")
    ) > 0
    # one receipt carries every serve key: the older suites stay enforced
    for key in ("serve_tokens_per_sec_speedup", "serve_p99_ttft_s",
                "serve_spec_speedup_vs_engine", "serve_prefix_warm_ttft_s"):
        assert key in gate


def test_committed_serve_router_receipt_satisfies_the_gate():
    """The committed PR 15 receipt must pass its own gate and meet the
    acceptance floors: every request terminal ROUTER-wide, zero leaked
    blocks summed across all replicas (the killed one included),
    survivors token-identical to the fault-free reference pass — and the
    drill really did kill one replica mid-trace and drain another."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_serve_router_pr15.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    router = receipt["router"]
    assert gate["serve_router_all_terminal"] == 1
    assert gate["serve_router_zero_leaked_blocks"] == 1
    assert gate["serve_router_survivor_token_identical"] == 1
    assert gate["serve_router_failover_p99_ttft_s"] > 0
    assert gate["serve_router_hot_tenant_cold_p99_ttft_s"] > 0
    assert router["leaked_blocks"] == 0
    assert router["all_terminal"] is True
    assert router["survivor_token_identical"] is True
    assert router["survivors_ok"] > 0
    # the drill is real: a replica died mid-trace, another drained out,
    # and live requests actually failed over
    assert router["kill_fired"] is True
    assert router["drain_fired"] is True
    assert router["failovers"] > 0
    assert router["drain_verdict"]["drained_clean"] is True
    assert router["drain_verdict"]["replica"] == router["config"]["drain_replica"]
    # one receipt carries every serve key: the older suites stay enforced
    for key in ("serve_tokens_per_sec_speedup", "serve_p99_ttft_s",
                "serve_spec_speedup_vs_engine", "serve_prefix_warm_ttft_s",
                "serve_chaos_goodput_tokens_per_sec"):
        assert key in gate


def test_committed_elastic_receipt_satisfies_the_gate():
    """The committed PR 7 receipt must pass its own gate and certify exact
    resumption: 0 steps replayed, a resumable preemption verdict."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_elastic_pr07.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    assert receipt["steps_replayed"] == 0
    assert receipt["gate"]["elastic_exact_resume"] == 1.0
    assert receipt["save_on_preempt_latency_s"] > 0
    assert receipt["time_to_resume_s"] > 0
    assert receipt["requeue_verdict"]["requeue"] is True


# -------------------------------------------------------------- data suite

DATA_RECEIPT = {
    "value_source": "cpu_smoke",
    "padding_waste_reclaimed": 0.5,
    "gate": {
        "data_packed_speedup_vs_pad": 2.5,
        "data_packed_tokens_per_sec": 7000.0,
        "data_padding_waste_reclaimed": 0.5,
        "data_zero_recompiles": 1.0,
        "data_wait_s": 0.04,
        "data_disk_tokens_per_sec": 7500.0,
        "data_disk_pad_fraction": 0.005,
        "data_disk_wait_s": 0.04,
        "data_disk_zero_replay": 1.0,
    },
}


def test_data_gate_passes_against_itself(tmp_path):
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    assert run_gate(base, current=dict(DATA_RECEIPT)) == 0


def test_data_gate_fails_against_doctored_regression(tmp_path, capsys):
    """A packed stream that stopped beating pad-to-max (the speedup
    collapses toward 1x) FAILS the gate."""
    doctored = json.loads(json.dumps(DATA_RECEIPT))
    doctored["gate"]["data_packed_speedup_vs_pad"] = 1.05
    doctored["gate"]["data_packed_tokens_per_sec"] = 2900.0
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    cur = _write(tmp_path, "doctored.json", doctored)
    assert run_gate(base, current=cur) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out and "data_packed_speedup_vs_pad" in out


def test_data_mid_run_recompile_fails(tmp_path, capsys):
    """A packed pipeline that started emitting ragged shapes (mid-run XLA
    compiles) reports data_zero_recompiles 0.0 — a 100% drop, always FAIL."""
    doctored = json.loads(json.dumps(DATA_RECEIPT))
    doctored["gate"]["data_zero_recompiles"] = 0.0
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "data_zero_recompiles" in capsys.readouterr().out


def test_data_wait_is_lower_is_better(tmp_path, capsys):
    """data_wait_s is a latency: growth past the wide latency tolerance
    (the packer falling back to a pathological path) fails; shrinking
    always passes."""
    slow = json.loads(json.dumps(DATA_RECEIPT))
    slow["gate"]["data_wait_s"] = 0.04 * 2.5  # > 2x baseline
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    assert run_gate(base, current=slow) == 1
    assert "data_wait_s" in capsys.readouterr().out
    fast = json.loads(json.dumps(DATA_RECEIPT))
    fast["gate"]["data_wait_s"] = 0.005
    assert run_gate(base, current=fast) == 0


def test_data_missing_metric_fails(tmp_path, capsys):
    """PR-6 semantics: a data metric that silently vanishes is a FAIL."""
    current = {"gate": {"data_packed_speedup_vs_pad": 2.5}}
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_data_disk_throughput_regression_fails(tmp_path, capsys):
    """A disk arm that stopped keeping up (reader starving the step, mmap
    path gone cold) FAILS on data_disk_tokens_per_sec."""
    doctored = json.loads(json.dumps(DATA_RECEIPT))
    doctored["gate"]["data_disk_tokens_per_sec"] = 4000.0
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "data_disk_tokens_per_sec" in capsys.readouterr().out


def test_data_disk_pad_fraction_is_lower_is_better(tmp_path, capsys):
    """Pad fraction growing back toward the greedy packer's 19% is the FFD
    win silently regressing — growth fails, shrinking passes."""
    worse = json.loads(json.dumps(DATA_RECEIPT))
    worse["gate"]["data_disk_pad_fraction"] = 0.15  # FFD win regressed away
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    assert run_gate(base, current=worse) == 1
    assert "data_disk_pad_fraction" in capsys.readouterr().out
    better = json.loads(json.dumps(DATA_RECEIPT))
    better["gate"]["data_disk_pad_fraction"] = 0.001
    assert run_gate(base, current=better) == 0


def test_data_disk_replay_failure_fails(tmp_path, capsys):
    """The reshard replay drill reporting even one replayed/skipped record
    (data_disk_zero_replay 0.0) is a 100% drop — always FAIL."""
    doctored = json.loads(json.dumps(DATA_RECEIPT))
    doctored["gate"]["data_disk_zero_replay"] = 0.0
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "data_disk_zero_replay" in capsys.readouterr().out


def test_data_missing_disk_metric_fails(tmp_path, capsys):
    """A receipt that silently drops the disk keys (bench arm deleted,
    marker renamed) FAILS — PR-6 missing-metric semantics cover the new
    keys too."""
    current = json.loads(json.dumps(DATA_RECEIPT))
    for k in list(current["gate"]):
        if k.startswith("data_disk_"):
            del current["gate"][k]
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_gate_main_data_suite_with_explicit_files(tmp_path):
    base = _write(tmp_path, "BENCH_data_base.json", DATA_RECEIPT)
    cur = _write(tmp_path, "cur.json", DATA_RECEIPT)
    assert gate_main(["--gate", "--suite", "data", "--baseline", base, "--current", cur]) == 0


def test_committed_data_receipt_satisfies_the_gate():
    """The committed PR 9 receipt must pass its own gate, beat pad-to-max
    by the acceptance floor (1.3x real tokens/s), report the padding waste
    reclaimed, certify 0 mid-run recompiles, and be honest about where it
    ran."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_data_pr09.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    assert receipt["gate"]["data_packed_speedup_vs_pad"] >= 1.3
    assert receipt["gate"]["data_padding_waste_reclaimed"] > 0.3
    assert receipt["gate"]["data_zero_recompiles"] == 1.0
    assert receipt["value_source"] == "cpu_smoke"
    assert receipt["pad_to_max"]["recompiles"] == 0
    assert receipt["packed_stream"]["recompiles"] == 0
    # both arms trained the same corpus: real token counts agree to within
    # the dropped-remainder batches
    pad_tok = receipt["pad_to_max"]["real_tokens_per_epoch"]
    packed_tok = receipt["packed_stream"]["real_tokens_per_epoch"]
    assert abs(pad_tok - packed_tok) / pad_tok < 0.1


def test_committed_disk_receipt_satisfies_the_gate():
    """The committed PR 18 receipt: the COLD-DISK arm beats the same-box
    in-memory greedy arm on real tokens/s (the mmap+read-ahead path costs
    nothing the FFD packing win doesn't repay), FFD holds pad_fraction at
    or under the 0.10 acceptance target (vs ~0.19 greedy), the 4->2
    reshard replay drill reports exactly zero replayed/skipped records,
    no arm recompiled mid-run, data_wait stays flat vs the in-memory arm,
    and the receipt carries the host fingerprint that scopes its absolute
    numbers to the box they were measured on."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_data_pr18.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    # disk-native floor: cold disk >= the in-memory packed arm, same box
    assert gate["data_disk_tokens_per_sec"] >= gate["data_packed_tokens_per_sec"]
    assert gate["data_disk_pad_fraction"] <= 0.10
    assert gate["data_disk_zero_replay"] == 1.0
    assert gate["data_zero_recompiles"] == 1.0
    assert receipt["disk_stream"]["recompiles"] == 0
    # data_wait flat: the reader's read-ahead keeps disk latency off the
    # training thread (within 2x of the in-memory arm's wait)
    assert gate["data_disk_wait_s"] <= 2.0 * gate["data_wait_s"]
    # end-of-stream flush is the ONLY boundary padding in FFD mode
    pack = receipt["disk_stream"]["pack"]
    assert pack["boundary_pad_slots"] == pack["pad_slots"]
    # absolute tokens/s are scoped to a box: the fingerprint must be there
    assert set(receipt["host"]) >= {"cpu_count", "platform", "python"}
    assert receipt["value_source"] == "cpu_smoke"
    # the boundary loss is reported and small relative to total padding
    pack = receipt["packed_stream"]["pack"]
    assert 0.0 <= pack["boundary_fraction"] <= pack["pad_fraction"]


# ------------------------------------------- serve suite: Medusa decoding

SERVE_MEDUSA_RECEIPT = {
    "value_source": "cpu_smoke",
    "gate": {
        "serve_tokens_per_sec_speedup": 3.0,
        "serve_engine_tokens_per_sec": 300.0,
        "serve_p99_ttft_s": 1.5,
        "serve_medusa_speedup_vs_engine": 1.3,
        "serve_medusa_accept_rate": 0.6,
        "serve_medusa_tokens_per_sec": 390.0,
        "serve_medusa_p99_ttft_s": 1.6,
        "serve_medusa_token_identical": 1,
        "serve_medusa_zero_recompiles": 1,
        "serve_medusa_zero_draft_blocks": 1,
    },
}


def test_serve_medusa_speedup_regression_fails(tmp_path, capsys):
    """Medusa decode falling back under the plain engine's throughput
    (speedup to ~1x) FAILS against the committed receipt."""
    doctored = json.loads(json.dumps(SERVE_MEDUSA_RECEIPT))
    doctored["gate"]["serve_medusa_speedup_vs_engine"] = 1.0
    doctored["gate"]["serve_medusa_tokens_per_sec"] = 300.0
    base = _write(tmp_path, "BENCH_serve_medusa_base.json", SERVE_MEDUSA_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "serve_medusa_speedup_vs_engine" in capsys.readouterr().out


def test_serve_medusa_contracts_are_pass_fail(tmp_path, capsys):
    """Token identity, zero recompiles AND the deleted-draft-pool contract
    (zero draft blocks allocated, pool clean) ride the gate as 1/0 ints:
    flipping any to 0 is a 100% drop — FAIL."""
    for key in (
        "serve_medusa_token_identical",
        "serve_medusa_zero_recompiles",
        "serve_medusa_zero_draft_blocks",
    ):
        doctored = json.loads(json.dumps(SERVE_MEDUSA_RECEIPT))
        doctored["gate"][key] = 0
        base = _write(tmp_path, f"BENCH_serve_{key}.json", SERVE_MEDUSA_RECEIPT)
        assert run_gate(base, current=doctored) == 1
        assert key in capsys.readouterr().out


def test_serve_medusa_missing_metric_fails(tmp_path, capsys):
    """A medusa metric that silently vanishes from the current run (the
    medusa arm stopped running at all) is a FAIL, not a pass."""
    current = json.loads(json.dumps(SERVE_MEDUSA_RECEIPT))
    del current["gate"]["serve_medusa_accept_rate"]
    base = _write(tmp_path, "BENCH_serve_medusa_base.json", SERVE_MEDUSA_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_serve_medusa_p99_ttft_is_lower_is_better(tmp_path):
    fast = json.loads(json.dumps(SERVE_MEDUSA_RECEIPT))
    fast["gate"]["serve_medusa_p99_ttft_s"] = 0.2  # improvement: passes
    base = _write(tmp_path, "BENCH_serve_medusa_base.json", SERVE_MEDUSA_RECEIPT)
    assert run_gate(base, current=fast) == 0
    slow = json.loads(json.dumps(SERVE_MEDUSA_RECEIPT))
    slow["gate"]["serve_medusa_p99_ttft_s"] = 1.6 * 2.5  # > 2x: regression
    assert run_gate(base, current=slow) == 1


def test_committed_serve_medusa_receipt_satisfies_the_gate():
    """The committed PR 16 receipt must pass its own gate and meet the
    acceptance floors: medusa tokens/s at least the plain engine's, zero
    draft-pool blocks allocated (the deleted second pool), survivors
    token-identical to serial generate, zero mid-run recompiles inside a
    budget STRICTLY below spec mode's."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_serve_medusa_pr16.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    assert gate["serve_medusa_speedup_vs_engine"] >= 1.0
    assert gate["serve_medusa_token_identical"] == 1
    assert gate["serve_medusa_zero_recompiles"] == 1
    assert gate["serve_medusa_zero_draft_blocks"] == 1
    medusa = receipt["medusa"]
    assert medusa["medusa_engine"]["draft_pool_blocks"] == 0
    assert medusa["medusa_engine"]["leaked_blocks"] == 0
    assert medusa["medusa_engine"]["compiled_signatures"] <= medusa["medusa_engine"]["max_signatures"]
    # the signature budget SHRANK vs spec mode — no draft signatures
    assert medusa["max_signatures_vs_spec_mode"] < 0
    # the spec-mode keys must still be present — medusa is a sibling mode,
    # not a replacement (the pr10 contract stays enforced)
    for key in ("serve_spec_speedup_vs_engine", "serve_spec_accept_rate"):
        assert key in gate


# ------------------------------------------ kernels suite: quantized training

TRAIN_QUANT_RECEIPT = {
    "value_source": "cpu_smoke",
    "gate": {
        "train_int8_speedup_vs_bf16": 1.5,
        "train_int8_steps_per_sec": 2.0,
        "train_int8_tokens_per_sec": 1500.0,
        "train_int8_loss_trajectory_ok": 1,
    },
}


def test_train_quant_speedup_regression_fails(tmp_path, capsys):
    """The int8 step sliding back to bf16 speed (speedup ~1x) FAILS
    against the committed receipt."""
    doctored = json.loads(json.dumps(TRAIN_QUANT_RECEIPT))
    doctored["gate"]["train_int8_speedup_vs_bf16"] = 1.0
    base = _write(tmp_path, "BENCH_train_quant_base.json", TRAIN_QUANT_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "train_int8_speedup_vs_bf16" in capsys.readouterr().out


def test_train_quant_loss_trajectory_is_pass_fail(tmp_path, capsys):
    """The loss-trajectory acceptance bound rides the gate as a 1/0 int: a
    trajectory that diverges from the bf16 baseline flips it to 0 — FAIL."""
    doctored = json.loads(json.dumps(TRAIN_QUANT_RECEIPT))
    doctored["gate"]["train_int8_loss_trajectory_ok"] = 0
    base = _write(tmp_path, "BENCH_train_quant_base.json", TRAIN_QUANT_RECEIPT)
    assert run_gate(base, current=doctored) == 1
    assert "train_int8_loss_trajectory_ok" in capsys.readouterr().out


def test_train_quant_missing_metric_fails(tmp_path, capsys):
    current = json.loads(json.dumps(TRAIN_QUANT_RECEIPT))
    del current["gate"]["train_int8_speedup_vs_bf16"]
    base = _write(tmp_path, "BENCH_train_quant_base.json", TRAIN_QUANT_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_gate_main_kernels_suite_merges_train_receipts(tmp_path, monkeypatch):
    """Without --baseline, the kernels suite folds BENCH_kernels_*.json AND
    BENCH_train_*.json into one merged baseline: the train_int8_* keys stay
    enforced (missing = FAIL) next to the kernel ratios."""
    import bench as bench_mod

    _write(tmp_path, "BENCH_kernels_a.json", RECEIPT)
    _write(tmp_path, "BENCH_train_quant_b.json", TRAIN_QUANT_RECEIPT)
    monkeypatch.setattr(
        bench_mod.os.path, "dirname", lambda p, _real=bench_mod.os.path.dirname: str(tmp_path)
    )
    both = {"gate": {**RECEIPT["gate"], **TRAIN_QUANT_RECEIPT["gate"]}}
    cur = _write(tmp_path, "cur.json", both)
    assert gate_main(["--gate", "--suite", "kernels", "--current", cur]) == 0
    # drop the train keys: the merged baseline still carries them — FAIL
    partial = _write(tmp_path, "partial.json", {"gate": dict(RECEIPT["gate"])})
    assert gate_main(["--gate", "--suite", "kernels", "--current", partial]) == 1


def test_committed_train_quant_receipt_satisfies_the_gate():
    """The committed PR 16 receipt must pass its own gate and meet the
    acceptance floors: int8 steps/s >= 1.15x the bf16 baseline on the
    pinned CPU-smoke config, with the loss trajectory inside the bound."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_train_quant_pr16.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    assert gate["train_int8_speedup_vs_bf16"] >= 1.15
    assert gate["train_int8_loss_trajectory_ok"] == 1
    assert receipt["loss_rel_gap_final_epoch"] <= receipt["config"]["loss_rel_bound"]
    assert receipt["value_source"] == "cpu_smoke"
    # both arms trained: per-epoch losses descend in both
    assert receipt["bf16"]["epoch_losses"][-1] < receipt["bf16"]["epoch_losses"][0]
    assert receipt["int8"]["epoch_losses"][-1] < receipt["int8"]["epoch_losses"][0]
    # receipts carry their host fingerprint (cross-host floors warn)
    assert receipt["host"]["cpu_count"] >= 1


# ------------------------------------------------- host fingerprint warning


def test_cross_host_baseline_warns_on_absolute_keys(tmp_path, capsys):
    """A baseline recorded on a different box WARNS about its absolute
    (non-ratio) keys — tokens/s floors don't transfer between hosts — but
    does not fail the gate by itself."""
    import bench as bench_mod

    foreign = json.loads(json.dumps(TRAIN_QUANT_RECEIPT))
    foreign["host"] = {"cpu_count": 999, "platform": "somewhere-else", "python": "3.10.0"}
    base = _write(tmp_path, "BENCH_train_quant_foreign.json", foreign)
    assert run_gate(base, current=dict(TRAIN_QUANT_RECEIPT)) == 0
    err = capsys.readouterr().err
    assert "WARNING" in err and "different host" in err
    assert "train_int8_tokens_per_sec" in err  # the absolute key is named
    assert "train_int8_speedup_vs_bf16" not in err  # ratios are portable
    # same host: silent
    local = json.loads(json.dumps(TRAIN_QUANT_RECEIPT))
    local["host"] = bench_mod._host_fingerprint()
    base2 = _write(tmp_path, "BENCH_train_quant_local.json", local)
    assert run_gate(base2, current=dict(TRAIN_QUANT_RECEIPT)) == 0
    assert "WARNING" not in capsys.readouterr().err


# -------------------------------------------------------- tier-1 wall suite

TIER1_RECEIPT = {
    "value_source": "cpu_smoke",
    "gate": {"tier1_suite_wall_s": 600.0, "tier1_exit_ok": 1},
}


def test_tier1_wall_is_lower_is_better(tmp_path):
    """The suite wall time is a latency: getting faster passes, quietly
    doubling past the latency tolerance FAILS before CI times out."""
    base = _write(tmp_path, "BENCH_tier1_base.json", TIER1_RECEIPT)
    fast = json.loads(json.dumps(TIER1_RECEIPT))
    fast["gate"]["tier1_suite_wall_s"] = 300.0
    assert run_gate(base, current=fast) == 0
    slow = json.loads(json.dumps(TIER1_RECEIPT))
    slow["gate"]["tier1_suite_wall_s"] = 600.0 * 2.5
    assert run_gate(base, current=slow) == 1
    broken = json.loads(json.dumps(TIER1_RECEIPT))
    broken["gate"]["tier1_exit_ok"] = 0  # suite went red: pass/fail int
    assert run_gate(base, current=broken) == 1


def test_committed_tier1_receipt_satisfies_the_gate():
    """The committed tier-1 budget receipt: green suite, wall time inside
    the 870s CI budget."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    import glob

    receipts = sorted(glob.glob(os.path.join(here, "BENCH_tier1_*.json")))
    if not receipts:
        pytest.skip("receipt not committed yet")
    receipt = json.load(open(receipts[-1]))
    assert run_gate(receipts[-1], current=receipts[-1]) == 0
    assert receipt["gate"]["tier1_exit_ok"] == 1
    assert receipt["gate"]["tier1_suite_wall_s"] < 870.0


# ------------------------------------------- serve suite: observability

OBS_RECEIPT = {
    "value_source": "cpu_smoke",
    "gate": {
        "obs_overhead_frac": 0.01,
        "obs_trace_linked": 1,
        "obs_metrics_valid": 1,
    },
}


def test_obs_gate_passes_against_itself(tmp_path):
    base = _write(tmp_path, "BENCH_obs_base.json", OBS_RECEIPT)
    assert run_gate(base, current=dict(OBS_RECEIPT)) == 0


def test_obs_overhead_is_lower_is_better(tmp_path, capsys):
    """The instrumentation overhead fraction is a latency-class metric:
    growing past the wide latency tolerance FAILS naming the key,
    shrinking (cheaper tracing) always passes."""
    heavy = json.loads(json.dumps(OBS_RECEIPT))
    heavy["gate"]["obs_overhead_frac"] = 0.05  # 5x the committed cost
    base = _write(tmp_path, "BENCH_obs_base.json", OBS_RECEIPT)
    assert run_gate(base, current=heavy) == 1
    assert "obs_overhead_frac" in capsys.readouterr().out
    free = json.loads(json.dumps(OBS_RECEIPT))
    free["gate"]["obs_overhead_frac"] = 0.0
    assert run_gate(base, current=free) == 0


def test_obs_contracts_are_pass_fail(tmp_path, capsys):
    """Trace linkage and exposition validity are binary contracts: a
    single orphan span (linked -> 0) or an unparseable metrics page
    FAILS outright."""
    base = _write(tmp_path, "BENCH_obs_base.json", OBS_RECEIPT)
    for key in ("obs_trace_linked", "obs_metrics_valid"):
        broken = json.loads(json.dumps(OBS_RECEIPT))
        broken["gate"][key] = 0
        assert run_gate(base, current=broken) == 1
        assert key in capsys.readouterr().out


def test_obs_missing_metric_fails(tmp_path, capsys):
    """An obs metric that silently vanishes is a FAIL, like every suite."""
    current = {"gate": {"obs_overhead_frac": 0.0, "obs_trace_linked": 1}}
    base = _write(tmp_path, "BENCH_obs_base.json", OBS_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_gate_main_serve_suite_merges_obs_receipts(tmp_path, monkeypatch):
    """The serve suite's merged baseline folds BENCH_obs_*.json in next
    to the serve receipts: dropping an obs key from the current run
    FAILS even when every serve key is healthy."""
    import bench as bench_mod

    serve = {"gate": {"serve_p99_ttft_s": 1.5, "serve_tokens_per_sec_speedup": 3.0}}
    obs = {"gate": dict(OBS_RECEIPT["gate"])}
    _write(tmp_path, "BENCH_serve_a.json", serve)
    _write(tmp_path, "BENCH_obs_pr19.json", obs)
    monkeypatch.setattr(
        bench_mod.os.path, "dirname", lambda p, _real=bench_mod.os.path.dirname: str(tmp_path)
    )
    both = {"gate": {**serve["gate"], **obs["gate"]}}
    cur = _write(tmp_path, "cur.json", both)
    assert gate_main(["--gate", "--suite", "serve", "--current", cur]) == 0
    partial = _write(tmp_path, "partial.json", serve)
    assert gate_main(["--gate", "--suite", "serve", "--current", partial]) == 1


def test_committed_obs_receipt_satisfies_the_gate():
    """The committed PR 19 receipt must pass its own gate and meet the
    acceptance floors: instrumentation overhead inside the 3% budget,
    every request's spans linked into one trace with ZERO orphans
    through the kill-one-drain-one router drill, and both metrics
    surfaces parsing as valid Prometheus text."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_obs_pr19.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    assert gate["obs_overhead_frac"] <= 0.03
    assert gate["obs_trace_linked"] == 1
    assert gate["obs_metrics_valid"] == 1
    assert receipt["value_source"] == "cpu_smoke"
    overhead = receipt["overhead"]
    assert overhead["spans_journaled"] > 0
    assert overhead["engine_metrics_valid"] is True
    assert overhead["leaked_blocks"] == 0
    drill = receipt["router_drill"]
    # the drill is real: a replica died mid-trace, another drained out,
    # and every logical request still resolved to exactly one trace
    assert drill["kill_fired"] is True and drill["drain_fired"] is True
    assert drill["orphan_spans"] == 0
    assert drill["traces"] == drill["requests"]
    assert drill["all_terminal"] is True
    assert drill["leaked_blocks"] == 0
    assert drill["metrics_families"] > 0


# ---------------------------------------------- lint suite: IR verifier

VERIFY_RECEIPT = {
    "value_source": "cpu_smoke",
    "gate": {
        "verify_wall_s": 0.05,
        "verify_caught_donation": 1,
        "verify_caught_oom": 1,
    },
}


def test_verify_gate_passes_against_itself(tmp_path):
    base = _write(tmp_path, "BENCH_verify_base.json", VERIFY_RECEIPT)
    assert run_gate(base, current=dict(VERIFY_RECEIPT)) == 0


def test_verify_wall_is_lower_is_better(tmp_path, capsys):
    """The preflight wall time is a latency-class metric: growing past
    the wide latency tolerance FAILS naming the key, shrinking (a faster
    tracer) always passes."""
    slow = json.loads(json.dumps(VERIFY_RECEIPT))
    slow["gate"]["verify_wall_s"] = 1.0  # 20x the committed cost
    base = _write(tmp_path, "BENCH_verify_base.json", VERIFY_RECEIPT)
    assert run_gate(base, current=slow) == 1
    assert "verify_wall_s" in capsys.readouterr().out
    fast = json.loads(json.dumps(VERIFY_RECEIPT))
    fast["gate"]["verify_wall_s"] = 0.001
    assert run_gate(base, current=fast) == 0


def test_verify_caught_bits_are_pass_fail(tmp_path, capsys):
    """The doctored-regression lock: the dropped-donation and the
    HBM-exceeding defect are planted on every bench run, and a verifier
    that stops catching either one (bit -> 0) FAILS outright."""
    base = _write(tmp_path, "BENCH_verify_base.json", VERIFY_RECEIPT)
    for key in ("verify_caught_donation", "verify_caught_oom"):
        blind = json.loads(json.dumps(VERIFY_RECEIPT))
        blind["gate"][key] = 0
        assert run_gate(base, current=blind) == 1
        assert key in capsys.readouterr().out


def test_verify_missing_metric_fails(tmp_path, capsys):
    """A verify metric that silently vanishes is a FAIL, like every suite."""
    current = {"gate": {"verify_wall_s": 0.01, "verify_caught_donation": 1}}
    base = _write(tmp_path, "BENCH_verify_base.json", VERIFY_RECEIPT)
    assert run_gate(base, current=current) == 1
    assert "MISSING" in capsys.readouterr().out


def test_gate_main_lint_suite_merges_verify_receipts(tmp_path, monkeypatch):
    """The lint suite's merged baseline folds BENCH_verify_*.json in next
    to the lint receipts: dropping a verify key from the current run
    FAILS even when every lint key is healthy."""
    import bench as bench_mod

    lint = {"gate": {"lint_cold_wall_s": 5.0, "lint_warm_wall_s": 0.1,
                     "lint_incremental_ok": 1}}
    verify = {"gate": dict(VERIFY_RECEIPT["gate"])}
    _write(tmp_path, "BENCH_lint_a.json", lint)
    _write(tmp_path, "BENCH_verify_pr20.json", verify)
    monkeypatch.setattr(
        bench_mod.os.path, "dirname", lambda p, _real=bench_mod.os.path.dirname: str(tmp_path)
    )
    both = {"gate": {**lint["gate"], **verify["gate"]}}
    cur = _write(tmp_path, "cur.json", both)
    assert gate_main(["--gate", "--suite", "lint", "--current", cur]) == 0
    partial = _write(tmp_path, "partial.json", lint)
    assert gate_main(["--gate", "--suite", "lint", "--current", partial]) == 1


def test_committed_verify_receipt_satisfies_the_gate():
    """The committed PR 20 receipt must pass its own gate and meet the
    acceptance lock: BOTH doctored defects caught (the dropped donation
    DML205 passes clean, and the HBM-budget overrun)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_verify_pr20.json")
    if not os.path.exists(path):
        pytest.skip("receipt not committed yet")
    assert run_gate(path, current=path) == 0
    receipt = json.load(open(path))
    gate = receipt["gate"]
    assert gate["verify_caught_donation"] == 1
    assert gate["verify_caught_oom"] == 1
    assert gate["verify_wall_s"] > 0.0
    assert receipt["value_source"] == "cpu_smoke"
    assert receipt["programs"] == 2
