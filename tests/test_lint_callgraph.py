"""The PR-17 whole-program arm: DML5xx fixtures, the incremental cache,
baseline/autofix workflow, and the schema-v2 CLI contract.

Complements tests/test_lint.py (per-rule module fixtures): everything
here needs either the cross-file ProjectGraph pass, the LintCache, or the
new CLI flags. Cache tests build throwaway packages under tmp_path so
hash/graph invalidation can be exercised by actually editing files.
"""

import json
import os
import textwrap
from pathlib import Path
from unittest import mock

import pytest

from dmlcloud_tpu.lint import (
    DEFAULT_CACHE_PATH,
    FIXABLE_RULES,
    PROJECT_RULES,
    RULES,
    LintCache,
    apply_fixes,
    lint_paths,
)
from dmlcloud_tpu.lint.cli import main as lint_cli
from dmlcloud_tpu.lint.engine import expand_rule_ids

FIXTURES = Path(__file__).parent / "lint_fixtures"

#: package directory -> exact expected finding counts (and NOTHING else —
#: the clean companions in each package must stay silent)
PACKAGE_EXPECT = {
    "dml501": {"DML501": 2},
    "dml502": {"DML502": 3},
    "dml503": {"DML503": 2},
    "dml504": {"DML504": 2},
}


def _counts(findings):
    out = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out


# --------------------------------------------------------------------------
# fixture corpus: one package per project rule
# --------------------------------------------------------------------------
class TestProjectRuleFixtures:
    @pytest.mark.parametrize("pkg", sorted(PACKAGE_EXPECT))
    def test_package_flags_exactly_its_rule(self, pkg):
        findings = lint_paths([FIXTURES / pkg])
        assert _counts(findings) == PACKAGE_EXPECT[pkg], [f.format() for f in findings]

    @pytest.mark.parametrize("pkg", sorted(PACKAGE_EXPECT))
    def test_clean_files_stay_clean(self, pkg):
        rule = pkg.upper()
        findings = lint_paths([FIXTURES / pkg])
        flagged = {Path(f.path).name for f in findings if f.rule == rule}
        assert "clean.py" not in flagged

    def test_no_callgraph_disables_project_rules(self):
        for pkg in PACKAGE_EXPECT:
            findings = lint_paths([FIXTURES / pkg], callgraph=False)
            assert not any(f.rule.startswith("DML5") for f in findings), pkg

    def test_registered_as_project_rules_not_module_rules(self):
        assert set(PACKAGE_EXPECT).issubset({r.lower() for r in PROJECT_RULES})
        assert not set(PROJECT_RULES) & set(RULES)

    def test_family_wildcard_expands_project_rules(self):
        expanded, unknown = expand_rule_ids(["DML5xx"])
        assert not unknown
        assert set(expanded) == set(PROJECT_RULES)

    def test_dml502_subsumes_renamed_dml211_pattern(self):
        # the import-rename shim (_alias.py re-exports scatter_tokens as
        # table_write) defeats DML211's vocabulary scoping; DML502 resolves
        # the reference through the graph and still fires
        findings = lint_paths([FIXTURES / "dml502"])
        renamed = [f for f in findings if Path(f.path).name == "renamed.py"]
        assert len(renamed) == 1 and renamed[0].rule == "DML502"
        assert not any(f.rule in ("DML211", "DML212") for f in findings)

    def test_pool_path_matches_serial(self):
        # the 1-CPU collapse is tested in test_lint.py; here we force a real
        # ProcessPoolExecutor and require identical output
        serial = lint_paths([FIXTURES / p for p in sorted(PACKAGE_EXPECT)])
        with mock.patch.object(os, "cpu_count", return_value=2):
            pooled = lint_paths([FIXTURES / p for p in sorted(PACKAGE_EXPECT)], jobs=2)
        assert pooled == serial

    def test_jobs_collapse_on_single_core(self):
        serial = lint_paths([FIXTURES / "dml501"])
        with mock.patch.object(os, "cpu_count", return_value=1):
            collapsed = lint_paths([FIXTURES / "dml501"], jobs=4)
        assert collapsed == serial


# --------------------------------------------------------------------------
# incremental cache
# --------------------------------------------------------------------------
PKG_FILES = {
    "__init__.py": "",
    "pools.py": """
        class KVBlockPool:
            def __init__(self, n):
                self.free = list(range(n))

            def alloc(self, k):
                blocks = [self.free.pop() for _ in range(k)]
                return blocks

            def release(self, blocks):
                self.free.extend(blocks)
        """,
    "app.py": """
        from .pools import KVBlockPool


        def run(n):
            pool = KVBlockPool(n)
            blocks = pool.alloc(2)
            pool.release(blocks)
            return len(blocks)
        """,
    "helpers.py": """
        def double(x):
            return 2 * x
        """,
    "threads.py": """
        from .helpers import double


        def run(x):
            return double(x)
        """,
    "timing.py": """
        import time


        class TimerStage:
            def train_epoch(self):
                t0 = time.time()
                return t0
        """,
}


@pytest.fixture
def pkg(tmp_path):
    root = tmp_path / "pkg"
    root.mkdir()
    for name, body in PKG_FILES.items():
        (root / name).write_text(textwrap.dedent(body).lstrip("\n"))
    return root


def _run(pkg, cache, **kw):
    stats = {}
    findings = lint_paths([pkg], cache=cache, stats=stats, **kw)
    linted = {Path(p).name for p in stats["linted"]}
    reused = {Path(p).name for p in stats["reused"]}
    return findings, linted, reused


class TestLintCache:
    def test_cold_then_warm(self, pkg, tmp_path):
        cache = tmp_path / "cache.json"
        cold, linted, reused = _run(pkg, cache)
        assert linted == set(PKG_FILES) and reused == set()
        assert _counts(cold) == {"DML108": 1}

        warm, linted, reused = _run(pkg, cache)
        assert linted == set() and reused == set(PKG_FILES)
        assert warm == cold  # cached findings replay byte-identically

    def test_leaf_edit_relints_only_reverse_importers(self, pkg, tmp_path):
        cache = tmp_path / "cache.json"
        _run(pkg, cache)
        leaf = pkg / "helpers.py"
        leaf.write_text(leaf.read_text() + "\n\ndef triple(x):\n    return 3 * x\n")
        _, linted, reused = _run(pkg, cache)
        assert linted == {"helpers.py", "threads.py"}
        assert reused == set(PKG_FILES) - linted

    def test_hub_edit_relints_transitive_importers(self, pkg, tmp_path):
        cache = tmp_path / "cache.json"
        _run(pkg, cache)
        hub = pkg / "pools.py"
        hub.write_text(hub.read_text() + "\n\ndef capacity(pool):\n    return len(pool.free)\n")
        _, linted, reused = _run(pkg, cache)
        assert linted == {"pools.py", "app.py"}
        assert reused == set(PKG_FILES) - linted

    def test_config_change_drops_cache(self, pkg, tmp_path):
        cache = tmp_path / "cache.json"
        _run(pkg, cache)
        _, linted, _ = _run(pkg, cache, ignore=["DML108"])
        assert linted == set(PKG_FILES)  # different signature: full cold run

    def test_corrupt_cache_degrades_to_cold(self, pkg, tmp_path):
        cache = tmp_path / "cache.json"
        cold, _, _ = _run(pkg, cache)
        cache.write_text("{definitely not json")
        again, linted, reused = _run(pkg, cache)
        assert linted == set(PKG_FILES) and reused == set()
        assert again == cold

    def test_warm_run_honors_cached_suppressions(self, pkg, tmp_path):
        # a DML5xx finding suppressed in a cached file must stay suppressed
        # when the project pass replays from the cache (family wildcard too)
        (pkg / "leak.py").write_text(
            textwrap.dedent(
                """
                from .pools import KVBlockPool


                def leaky(pool: KVBlockPool, flag):
                    blocks = pool.alloc(1)  # dmllint: disable=DML5xx -- test fixture
                    if flag:
                        pool.release(blocks)
                    return flag
                """
            ).lstrip("\n")
        )
        cache = tmp_path / "cache.json"
        cold, _, _ = _run(pkg, cache)
        assert not any(f.rule == "DML501" for f in cold)
        warm, linted, _ = _run(pkg, cache)
        assert "leak.py" not in linted
        assert not any(f.rule == "DML501" for f in warm)

    def test_project_findings_track_cached_summaries(self, pkg, tmp_path):
        # introduce a leak in ONE file: the project pass must see it even
        # though every OTHER file replays from the cache
        cache = tmp_path / "cache.json"
        _run(pkg, cache)
        (pkg / "app.py").write_text(
            textwrap.dedent(
                """
                from .pools import KVBlockPool


                def run(n, flag):
                    pool = KVBlockPool(n)
                    blocks = pool.alloc(2)
                    if flag:
                        pool.release(blocks)
                    return flag
                """
            ).lstrip("\n")
        )
        findings, linted, _ = _run(pkg, cache)
        assert "app.py" in linted and "helpers.py" not in linted
        assert any(f.rule == "DML501" and Path(f.path).name == "app.py" for f in findings)

    def test_plan_api_shapes(self, pkg, tmp_path):
        cache_path = tmp_path / "cache.json"
        lint_paths([pkg], cache=cache_path)
        cache = LintCache(cache_path)
        files = sorted(str(p) for p in pkg.glob("*.py"))
        to_lint, reuse = cache.plan(files)
        assert to_lint == [] and sorted(reuse) == files
        assert isinstance(DEFAULT_CACHE_PATH, str)


# --------------------------------------------------------------------------
# CLI: schema v2, exit codes, baseline, autofix
# --------------------------------------------------------------------------
class TestCliWorkflow:
    def _json(self, capsys, *argv):
        rc = lint_cli(["--json", *argv])
        return rc, json.loads(capsys.readouterr().out)

    def test_schema_v2_and_v1_compatibility(self, capsys):
        rc, payload = self._json(capsys, str(FIXTURES / "dml501"))
        assert rc == 1
        assert payload["version"] == 2
        assert payload["status"] == "findings"
        # v1 compatibility contract: every v1 key is still present with the
        # same shape and meaning
        assert {"version", "files_scanned", "findings", "counts"} <= set(payload)
        assert payload["counts"] == {"DML501": 2}
        for f in payload["findings"]:
            assert {"rule", "path", "line", "col", "message", "context"} <= set(f)

    def test_parse_error_status_and_exit_2(self, tmp_path, capsys):
        bad = tmp_path / "broken.py"
        bad.write_text("def oops(:\n")
        rc, payload = self._json(capsys, str(bad))
        assert rc == 2
        assert payload["status"] == "parse_error"
        assert payload["counts"] == {"DML999": 1}

    def test_clean_status(self, tmp_path, capsys):
        (tmp_path / "ok.py").write_text("x = 1\n")
        rc, payload = self._json(capsys, str(tmp_path))
        assert rc == 0 and payload["status"] == "clean"

    def test_select_and_ignore_family_wildcards(self, capsys):
        rc, payload = self._json(capsys, "--select", "DML5xx", str(FIXTURES / "dml503"))
        assert rc == 1 and payload["counts"] == {"DML503": 2}
        rc, payload = self._json(capsys, "--ignore", "DML5xx", str(FIXTURES / "dml503"))
        assert rc == 0 and payload["findings"] == []

    def test_baseline_roundtrip(self, tmp_path, capsys):
        target = tmp_path / "legacy.py"
        target.write_text(
            "import time\n\n\nclass LegacyStage:\n"
            "    def train_epoch(self):\n        return time.time()\n"
        )
        baseline = tmp_path / "baseline.json"
        assert lint_cli([str(target), "--write-baseline", str(baseline)]) == 0
        capsys.readouterr()
        # frozen findings are filtered out...
        rc, payload = self._json(capsys, "--baseline", str(baseline), str(target))
        assert rc == 0 and payload["status"] == "clean"
        # ...but NEW findings still surface
        target.write_text(
            target.read_text() + "\n    def val_epoch(self):\n        return time.time()\n"
        )
        rc, payload = self._json(capsys, "--baseline", str(baseline), str(target))
        assert rc == 1 and payload["counts"] == {"DML108": 1}

    def test_missing_baseline_is_usage_error(self, tmp_path, capsys):
        rc = lint_cli(["--baseline", str(tmp_path / "nope.json"), str(tmp_path)])
        assert rc == 2

    def test_fix_rewrites_and_is_idempotent(self, tmp_path, capsys):
        assert "DML108" in FIXABLE_RULES
        target = tmp_path / "fixme.py"
        target.write_text(
            "import time\n\n\nclass FixStage:\n    def train_epoch(self):\n"
            "        t0 = time.time()\n        return time.time() - t0\n"
        )
        rc, payload = self._json(capsys, "--fix", str(target))
        assert rc == 0 and payload["status"] == "clean"
        fixed = target.read_text()
        assert "time.time()" not in fixed and fixed.count("time.perf_counter()") == 2
        rc, _ = self._json(capsys, "--fix", str(target))
        assert rc == 0 and target.read_text() == fixed  # second run is a no-op

    def test_fix_suppress_inserts_directives(self, tmp_path, capsys):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "machine.py").write_text((FIXTURES / "dml503" / "machine.py").read_text())
        rc = lint_cli(["--fix-suppress", str(pkg)])
        capsys.readouterr()
        assert rc == 0
        text = (pkg / "machine.py").read_text()
        assert text.count("# dmllint: disable=DML503") == 2
        assert lint_cli([str(pkg)]) == 0
        capsys.readouterr()

    def test_apply_fixes_only_touches_finding_lines(self, tmp_path):
        target = tmp_path / "partial.py"
        target.write_text(
            "import time\n\n\nclass MixStage:\n    def train_epoch(self):\n"
            "        clock = time.time  # reference on a non-finding line\n"
            "        t0 = time.time()\n        return clock, t0\n"
        )
        apply_fixes(lint_paths([target], callgraph=False))
        text = target.read_text()
        assert "clock = time.time  #" in text  # non-finding line untouched
        assert "t0 = time.perf_counter()" in text

    def test_cache_flag_end_to_end(self, tmp_path, capsys, monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "mod.py").write_text("x = 1\n")
        assert lint_cli(["--cache", "--json", "mod.py"]) == 0
        capsys.readouterr()
        assert (tmp_path / DEFAULT_CACHE_PATH).is_file()
        assert lint_cli(["--cache", "--json", "mod.py"]) == 0
        capsys.readouterr()

    def test_list_rules_tags_project_scope(self, capsys):
        assert lint_cli(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in PROJECT_RULES:
            assert f"{rid}" in out
        assert "[project]" in out


# --------------------------------------------------------------------------
# self-analysis lock: the codebase itself must hold its own contracts
# --------------------------------------------------------------------------
class TestSelfAnalysis:
    @pytest.mark.slow
    def test_whole_program_pass_is_clean_on_repo(self):
        repo = Path(__file__).parent.parent
        targets = [repo / "dmlcloud_tpu", repo / "examples", repo / "bench.py", repo / "scripts"]
        findings = lint_paths([t for t in targets if t.exists()])
        dml5 = [f for f in findings if f.rule.startswith("DML5")]
        assert dml5 == [], [f.format() for f in dml5]
