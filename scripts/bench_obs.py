#!/usr/bin/env python
"""Observability-plane receipt (doc/observability.md): what does the serve
observability plane COST, and does it actually link?

- the OVERHEAD arm: two engines replay the pinned CPU-smoke Poisson serve
  trace (the same one ``bench_serve`` uses) — one bare, one with the full
  plane armed at once (span journal flushing off-thread, the typed
  metrics registry's hot-path counters/histograms, SLO monitors evaluated
  every step). Best-of-N tokens/s per arm against CPU scheduler noise;
  ``obs_overhead_frac`` is the lower-is-better fraction the committed
  receipt locks at ≤3% (tests/test_bench_gate.py).
- the LINKED-TRACE drill: the same kill-one-replica-drain-another router
  drill as the serve receipt, but with the span journal armed. Every span
  a request touches — across replicas, failover retries (the idempotency
  token rotates, the trace id does NOT), and the drained replica's
  handoff — must link into exactly one per-request trace with ZERO orphan
  request-scoped spans (``telemetry.journal.linked_trace_report``);
  ``obs_trace_linked`` is the pass/fail int.
- exposition validity: ``engine.metrics_text()`` and the router-wide
  ``Router.metrics_text()`` must parse as valid Prometheus text
  (``telemetry.metrics_registry.parse_prometheus_text``, the same strict
  validator the schema-lock test uses); ``obs_metrics_valid`` is the
  pass/fail int.

Thin CLI over ``bench.bench_obs`` (which runs ``bench.py --obs-child``
CPU-pinned) so the committed receipt and an interactive investigation run
the exact same workload. The receipt's flat ``gate`` section merges into
``bench.py --gate --suite serve`` / scripts/perf_gate.sh alongside every
committed BENCH_serve_*.json (missing metric = FAIL).

    JAX_PLATFORMS=cpu python scripts/bench_obs.py --out BENCH_obs_pr19.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None, help="also write the receipt JSON here")
    args = parser.parse_args()

    from bench import bench_obs

    results = bench_obs()
    if results is None:
        print("obs bench failed (child produced no results)", file=sys.stderr)
        return 1
    payload = json.dumps(results, indent=2)
    print(payload)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
