#!/usr/bin/env python
"""Cold-start A/B receipt: persistent-cache time-to-first-step (cold vs
warm, fresh process each) and ragged-batch compiled-signature growth with
vs without shape buckets (doc/performance.md §4).

Thin CLI over ``bench.bench_compile`` (which runs ``bench.py
--compile-child`` CPU-pinned) so the committed receipt and an interactive
investigation run the exact same workload.

    JAX_PLATFORMS=cpu python scripts/bench_compile.py --out BENCH_compile_pr03.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None, help="also write the receipt JSON here")
    parser.add_argument(
        "--smoke", action="store_true", help="tiny shapes (sets DML_BENCH_SMOKE for the children)"
    )
    args = parser.parse_args()
    if args.smoke:
        os.environ["DML_BENCH_SMOKE"] = "1"

    from bench import bench_compile

    results = bench_compile()
    if results is None:
        print("compile bench failed (child produced no results)", file=sys.stderr)
        return 1
    payload = json.dumps(results, indent=2)
    print(payload)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
