#!/usr/bin/env python
"""Kernel A/B receipt: the three hot-path kernels vs their baselines on the
pinned CPU-smoke configs (doc/performance.md §"Kernel receipts"):

- flash attention fwd AND fwd+bwd vs the unfused einsum reference
  (blockwise-XLA lowering + custom_vjp recompute-from-LSE backward)
- speculative decode vs plain greedy (on-device accept loop; includes the
  shared-model smoke where draft == target must accept at exactly 1.0)
- int8 weight-only decode (fused QuantDense, prepare_decode_params) vs bf16

Thin CLI over ``bench.bench_kernels`` (which runs ``bench.py
--kernels-child`` CPU-pinned) so the committed receipt and an interactive
investigation run the exact same workload. The receipt's flat ``gate``
section is what ``bench.py --gate`` / scripts/perf_gate.sh compares.

    JAX_PLATFORMS=cpu python scripts/bench_kernels.py --out BENCH_kernels_pr06.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None, help="also write the receipt JSON here")
    args = parser.parse_args()

    from bench import bench_kernels

    results = bench_kernels()
    if results is None:
        print("kernel bench failed (child produced no results)", file=sys.stderr)
        return 1
    payload = json.dumps(results, indent=2)
    print(payload)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
