"""ResNet-50 raw-step tuning harness (VERDICT r3 #2: raise raw_mfu >= 0.25).

Runs the bench's raw train step under a matrix of variants on the real chip
and prints images/s + MFU per variant, optionally capturing a
``jax.profiler`` trace of the best one for doc/performance.md analysis.

    python scripts/tune_resnet.py                 # sweep variants
    python scripts/tune_resnet.py --trace /tmp/tr # also trace the winner

Variants (each a delta on the bench's baseline step, bench.py:77-112):
- batch: 128 / 256 / 512 / 1024 (HBM permitting)
- input dtype: f32 (baseline) vs bf16 images (halves input HBM traffic)
- BN axis_name sync off (single chip) is already the baseline; 'fused_bn'
  folds scale/bias into conv output via XLA (it fuses these anyway — the
  variant exists to CONFIRM that with numbers, not to assume it)
"""

import argparse
import functools
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bench import IMG, TRAIN_FLOPS_PER_IMAGE, chip_peak_flops, make_model_and_state


def raw_step_fn(model, tx):
    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, batch):
        def loss_fn(p):
            logits, new_state = model.apply(
                {"params": p, "batch_stats": batch_stats},
                batch["image"], train=True, mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"]).mean()
            return loss, new_state["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_bs, new_opt, loss

    return train_step


def run_variant(batch_size: int, image_dtype, warmup=5, steps=30, trace_dir=None):
    model, variables, tx = make_model_and_state()
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt_state = tx.init(params)
    rng = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(rng.rand(batch_size, IMG, IMG, 3), image_dtype),
        "label": jnp.asarray(rng.randint(0, 1000, size=batch_size), jnp.int32),
    }
    step = raw_step_fn(model, tx)
    batch = jax.device_put(batch)
    for _ in range(warmup):
        params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state, batch)
    float(loss)  # value fetch: the only reliable sync on tunneled platforms
    ctx = jax.profiler.trace(trace_dir) if trace_dir else None
    if ctx:
        ctx.__enter__()
    t0 = time.perf_counter()
    for _ in range(steps):
        params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state, batch)
    float(loss)
    dt = time.perf_counter() - t0
    if ctx:
        ctx.__exit__(None, None, None)
    ips = steps * batch_size / dt
    return ips, ips * TRAIN_FLOPS_PER_IMAGE / chip_peak_flops()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", default=None, help="profile-trace dir for the best variant")
    ap.add_argument("--batches", default="128,256,512,1024")
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    print(f"device: {jax.devices()[0].device_kind}, peak {chip_peak_flops()/1e12:.0f} TF/s bf16")
    results = {}
    for b in [int(x) for x in args.batches.split(",")]:
        for dt_name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
            name = f"b{b}/{dt_name}"
            try:
                ips, mfu = run_variant(b, dt, steps=args.steps)
            except Exception as e:  # HBM exhaustion at large batches
                print(f"{name:>12}: FAILED {type(e).__name__}: {str(e)[:120]}")
                continue
            results[name] = (ips, mfu)
            print(f"{name:>12}: {ips:8.1f} img/s  MFU {mfu:.3f}", flush=True)
    if not results:
        sys.exit(1)
    best = max(results, key=lambda k: results[k][0])
    print(f"best: {best} -> {results[best][0]:.1f} img/s, MFU {results[best][1]:.3f}")
    if args.trace:
        b = int(best.split("/")[0][1:])
        dt = jnp.bfloat16 if best.endswith("bf16") else jnp.float32
        ips, mfu = run_variant(b, dt, steps=args.steps, trace_dir=args.trace)
        print(f"traced {best} -> {ips:.1f} img/s; trace in {args.trace}")


if __name__ == "__main__":
    main()
