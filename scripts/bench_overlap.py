#!/usr/bin/env python
"""Standalone overlap-engine A/B: steps/sec and host-stall fraction with the
engine on vs off, on whatever backend this process sees (pass
``JAX_PLATFORMS=cpu`` for the smoke configuration bench.py records).

Thin CLI over ``bench._overlap_config`` so the committed bench numbers and an
interactive investigation run the exact same workload.

    JAX_PLATFORMS=cpu python scripts/bench_overlap.py --steps 240 --batch 64
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--steps", type=int, default=240, help="train steps per epoch")
    parser.add_argument("--batch", type=int, default=64, help="batch size")
    args = parser.parse_args()

    from bench import _overlap_config

    with tempfile.TemporaryDirectory() as td:
        off = _overlap_config(False, args.steps, args.batch, os.path.join(td, "off"))
        on = _overlap_config(True, args.steps, args.batch, os.path.join(td, "on"))
    ratio = round(on["steps_per_sec"] / off["steps_per_sec"], 4)
    print(json.dumps({"on": on, "off": off, "steps_per_sec_ratio_on_vs_off": ratio}, indent=2))


if __name__ == "__main__":
    main()
