"""Bench receipt for the IR-level program verifier (doc/lint.md DML6xx):
verify wall seconds over pinned train + serve step configs, plus the two
defect-detection bits the PR-20 acceptance locks.

``verify_wall_s`` is the cost of the preflight CI pays on every
``lint --ir`` / ``python -m dmlcloud_tpu verify`` invocation — a
lower-is-better latency gated like ``lint_cold_wall_s``. The two
``verify_caught_*`` ints are pass/fail contracts measured on DOCTORED
programs: a dtype-mismatched donation that compiles clean (the silent
drop DML205 cannot see — DML601 must catch it) and a step whose declared
HBM budget it provably exceeds (DML604 must catch it). A verifier that
goes blind flips the bit to 0 and ``bench.py --gate --suite lint`` fails
on the committed receipt.

    JAX_PLATFORMS=cpu python scripts/bench_verify.py [-o BENCH_verify_pr20.json]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dmlcloud_tpu.lint.ir import ProgramSpec, verify_programs  # noqa: E402

#: pinned config: a donating train-style step (params + sgd update) and a
#: donating serve-style decode step (kv-cache append + logits) — small
#: enough for a CI box, shaped like the real programs the runtime arms
#: stage at precompile/engine-construction time
_DIM = 64


def _train_step(params, batch):
    w1, w2 = params
    h = jnp.tanh(batch["x"] @ w1)
    pred = h @ w2
    loss = jnp.mean((pred - batch["y"]) ** 2)
    g1, g2 = jax.grad(lambda p: jnp.mean(((jnp.tanh(batch["x"] @ p[0])) @ p[1] - batch["y"]) ** 2))(params)
    return (w1 - 0.05 * g1, w2 - 0.05 * g2), loss


def _serve_step(cache, params, token):
    h = token @ params
    cache = cache.at[:, -1].set(h)
    return cache, h @ params.T


def _pinned_specs():
    f32 = jnp.float32
    params = (jax.ShapeDtypeStruct((_DIM, _DIM), f32),
              jax.ShapeDtypeStruct((_DIM, _DIM), f32))
    batch = {"x": jax.ShapeDtypeStruct((8, _DIM), f32),
             "y": jax.ShapeDtypeStruct((8, _DIM), f32)}
    cache = jax.ShapeDtypeStruct((4, 16, _DIM), f32)
    w = jax.ShapeDtypeStruct((_DIM, _DIM), f32)
    tok = jax.ShapeDtypeStruct((4, _DIM), f32)
    return [
        ProgramSpec(name="train_step", fn=_train_step,
                    args=(params, batch), donate_argnums=(0,), kind="train"),
        ProgramSpec(name="serve_step", fn=_serve_step,
                    args=(cache, w, tok), donate_argnums=(0,), kind="serve"),
    ]


def _dropped_donation_step(state, batch):
    # int32 state donated, float32 state returned: compiles clean, aliases 0
    return state.astype(jnp.float32) * 2.0 + batch


def _doctored_specs():
    i32, f32 = jnp.int32, jnp.float32
    return [
        ProgramSpec(name="doctored_donation", fn=_dropped_donation_step,
                    args=(jax.ShapeDtypeStruct((64, 64), i32),
                          jax.ShapeDtypeStruct((64, 64), f32)),
                    donate_argnums=(0,)),
        ProgramSpec(name="doctored_oom", fn=lambda x: x @ x.T,
                    args=(jax.ShapeDtypeStruct((64, 64), f32),),
                    hbm_budget_bytes=1024),
    ]


def dml_verify_programs():
    """IR-verify hook: the bench child's pinned train+serve configs ARE
    verifiable programs — ``python -m dmlcloud_tpu verify scripts/`` (and
    the self-verify lock in test_selflint.py) audits the exact programs
    this bench times, so the receipt can never be measured on programs
    the verifier would reject."""
    return _pinned_specs()


def measure(repeats: int = 3) -> dict | None:
    """Best-of-N verify wall seconds over the pinned clean configs, plus
    the defect-detection bits on the doctored programs. Returns None if
    the clean configs themselves produce findings (the bench must measure
    the verifier, not fight it)."""
    wall_best = float("inf")
    programs = 0
    for _ in range(repeats):
        stats: dict = {}
        t0 = time.perf_counter()
        findings = verify_programs(_pinned_specs(), stats=stats)
        wall_best = min(wall_best, time.perf_counter() - t0)
        programs = stats.get("programs", 0)
        if findings:
            return None
    doctored = verify_programs(_doctored_specs())
    rules = {f.rule for f in doctored}
    return {
        "bench": "verify_preflight",
        "value_source": "cpu_smoke",
        "host": {
            "cpu_count": os.cpu_count(),
            "platform": platform.platform(),
            "python": platform.python_version(),
        },
        "programs": programs,
        "repeats_best_of": repeats,
        "gate": {
            "verify_wall_s": round(wall_best, 4),
            "verify_caught_donation": int("DML601" in rules),
            "verify_caught_oom": int("DML604" in rules),
        },
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("-o", "--output", default=os.path.join(REPO, "BENCH_verify_pr20.json"))
    parser.add_argument("--repeats", type=int, default=3)
    args = parser.parse_args(argv)

    receipt = measure(repeats=args.repeats)
    if receipt is None:
        print("bench_verify: FAIL — the pinned clean configs produced findings", file=sys.stderr)
        return 1
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(receipt, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(receipt, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
