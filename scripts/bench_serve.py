#!/usr/bin/env python
"""Serving A/B receipt: the continuous-batching engine (dmlcloud_tpu/serve/)
vs serial ``generate()`` calls on the pinned CPU-smoke Poisson request
trace, plus the SPECULATIVE arm — the ``spec_k`` engine vs the plain
engine on a pinned Markov trace with a trained target/draft pair
(doc/serving.md):

- tokens/s over the busy window for every arm (the engine batches up to
  ``max_slots`` decode streams; serial services one request at a time;
  the spec engine commits up to k+1 tokens per verify round)
- p50/p99 time-to-first-token under the same arrival process (serial TTFT
  is honest: one compiled program emits nothing until it returns)
- greedy token-identity of both engines against serial generate, the
  measured draft accept rate, compiled-signature counts against the
  TraceGuard budgets, and the spec arm's mid-run recompile count (must
  be 0)
- the PREFIX-CACHE arm: the ``prefix_cache=True`` engine vs the same
  engine uncached on the pinned 80%-shared-template trace — warm-template
  p50 TTFT (the near-zero-prefill headline), hit rate, prefill tokens
  saved, token-identity to the uncached engine, 0 mid-run recompiles
- the CHAOS arm: a hot-tenant deadline burst plus a cold trickle through
  a bounded admission queue (oldest-deadline shedding, tenant DRR) with
  ``serve.chaos.ChaosMonkey`` attached — goodput under injected faults,
  cold-tenant p99 TTFT, zero leaked blocks, every request terminal,
  fault survivors token-identical to the fault-free reference arm
- the ROUTER arm: three engine replicas behind one ``serve.Router`` on a
  Poisson two-tenant trace, one replica KILLED mid-trace (live requests
  fail over at-most-once) and one DRAINED (queued work migrates, a
  requeue verdict is written) — every request terminal router-wide, zero
  leaked blocks across all replicas, survivors token-identical to a
  fault-free pass, router-side p99 TTFT with failover inside the number

Thin CLI over ``bench.bench_serve`` (which runs ``bench.py --serve-child``
CPU-pinned) so the committed receipt and an interactive investigation run
the exact same workload. The receipt's flat ``gate`` section is what
``bench.py --gate --suite serve`` / scripts/perf_gate.sh compares
(``serve_*``, ``serve_spec_*``, ``serve_prefix_*``, ``serve_chaos_*`` and
``serve_router_*`` keys, against EVERY committed BENCH_serve_*.json;
missing metric = FAIL).

    JAX_PLATFORMS=cpu python scripts/bench_serve.py --out BENCH_serve_router_pr15.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None, help="also write the receipt JSON here")
    args = parser.parse_args()

    from bench import bench_serve

    results = bench_serve()
    if results is None:
        print("serve bench failed (child produced no results)", file=sys.stderr)
        return 1
    payload = json.dumps(results, indent=2)
    print(payload)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
