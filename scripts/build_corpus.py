#!/usr/bin/env python
"""Corpus builder: turn a document stream into a ``.dmlshard`` corpus dir.

Writes the disk-native format read by ``dmlcloud_tpu.data.ShardStore`` /
``ShardReader`` (doc/data.md, "On-disk shard format"): fixed-header,
checksummed, memory-mappable shard files plus a ``corpus.json`` manifest.
Two input modes:

- ``--jsonl FILE``: one document per line — either a JSON array of token
  ids or an object with a ``"tokens"`` key. ``-`` reads stdin, so any
  tokenizer can pipe straight in.
- ``--synthetic N``: N documents with lognormal lengths from a pinned
  seed — the same generator family as the BENCH_data_* receipts, handy
  for smoke-testing the disk plane without a real corpus.

    python scripts/build_corpus.py --synthetic 768 --out /tmp/corpus
    python scripts/build_corpus.py --jsonl docs.jsonl --out corpus/ --shard-tokens 4194304

Verify the result with ``python -m dmlcloud_tpu diag --corpus corpus/``.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _jsonl_docs(path):
    import numpy as np

    stream = sys.stdin if path == "-" else open(path)
    try:
        for lineno, line in enumerate(stream, 1):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if isinstance(obj, dict):
                obj = obj.get("tokens")
            if not isinstance(obj, list):
                raise SystemExit(f"{path}:{lineno}: expected a token array or {{'tokens': [...]}}")
            yield np.asarray(obj, np.int32)
    finally:
        if stream is not sys.stdin:
            stream.close()


def _synthetic_docs(n, vocab, len_median, len_sigma, min_len, max_len, seed):
    import numpy as np

    rs = np.random.RandomState(seed)
    lengths = np.clip(
        np.round(rs.lognormal(np.log(len_median), len_sigma, n)), min_len, max_len
    ).astype(np.int64)
    for length in lengths:  # token ids from [1, vocab): id 0 stays the pad id
        yield rs.randint(1, vocab, size=int(length)).astype(np.int32)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--jsonl", help="one JSON doc per line (array or {'tokens': [...]}); '-' = stdin")
    src.add_argument("--synthetic", type=int, metavar="N", help="generate N synthetic documents")
    parser.add_argument("--out", required=True, help="corpus directory (created if missing)")
    parser.add_argument("--shard-tokens", type=int, default=1 << 22, help="roll a new shard past this many tokens")
    parser.add_argument("--prefix", default="corpus", help="shard filename prefix")
    parser.add_argument("--vocab", type=int, default=512, help="synthetic: vocab size")
    parser.add_argument("--len-median", type=float, default=64, help="synthetic: median doc length")
    parser.add_argument("--len-sigma", type=float, default=0.6, help="synthetic: lognormal sigma")
    parser.add_argument("--min-len", type=int, default=4, help="synthetic: min doc length")
    parser.add_argument("--max-len", type=int, default=256, help="synthetic: max doc length")
    parser.add_argument("--seed", type=int, default=0, help="synthetic: RNG seed")
    args = parser.parse_args()

    from dmlcloud_tpu.data.store import build_corpus

    if args.jsonl is not None:
        docs = _jsonl_docs(args.jsonl)
    else:
        docs = _synthetic_docs(
            args.synthetic, args.vocab, args.len_median, args.len_sigma,
            args.min_len, args.max_len, args.seed,
        )
    manifest = build_corpus(args.out, docs, shard_tokens=args.shard_tokens, prefix=args.prefix)
    print(
        f"wrote {len(manifest['shards'])} shard(s), {manifest['total_records']} record(s), "
        f"{manifest['total_tokens']} token(s) -> {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
