#!/usr/bin/env bash
# CI perf gate: the current kernel ratios (flash fwd / fwd+bwd vs unfused,
# speculative speedup + accept rate, int8 decode) and goodput fraction must
# not drop more than the tolerance below the last committed
# BENCH_kernels_*.json receipt (doc/performance.md §"Kernel receipts").
# Runs after the lint gate in the CI flow:
#
#     scripts/lint_gate.sh && scripts/perf_gate.sh
#
# Usage: scripts/perf_gate.sh [extra gate args, e.g. --tolerance 0.2
#        --baseline BENCH_kernels_pr06.json --current fresh.json]
# With no --current the gate measures fresh ratios in a CPU-pinned child
# (a few minutes); exit 0 pass, 1 regression, 2 could-not-measure.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python bench.py --gate "$@"
