#!/usr/bin/env bash
# CI perf gate, five suites (doc/performance.md §"Kernel receipts",
# doc/elasticity.md, doc/serving.md, doc/data.md):
#
#   kernels  current kernel ratios (flash fwd / fwd+bwd vs unfused,
#            speculative speedup + accept rate, int8 decode) PLUS the
#            quantized-training A/B (int8 vs bf16 steps/s through the real
#            TrainValStage, loss-trajectory pass/fail) and goodput
#            fraction vs EVERY committed BENCH_kernels_*.json and
#            BENCH_train_*.json merged into one baseline — a fresh run
#            measures BOTH children, so a vanished train_int8_* key FAILS
#   elastic  the preemption drill (SIGTERM mid-epoch on 4 devices, resume
#            on 2) vs the last committed BENCH_elastic_*.json — exact
#            resume (0 replayed steps), save-on-preempt latency,
#            time-to-resume; a missing metric FAILS
#   serve    the continuous-batching serving A/B (Poisson trace, engine vs
#            serial generate, the spec arm, the Medusa arm, the
#            prefix-cache arm, the chaos arm, the multi-replica router
#            drill) vs EVERY committed BENCH_serve_*.json merged into one
#            baseline (each key at its most recently committed value) —
#            tokens/s speedup, p99 TTFT, serve_spec_* accept/speedup keys,
#            serve_medusa_* speedup / zero-draft-blocks keys,
#            serve_prefix_* warm-TTFT / hit-rate keys, serve_chaos_*
#            robustness keys, serve_router_* failover/drain keys
#            (latencies lower-is-better; every receipt's keys stay
#            enforced, missing metric = FAIL); when a BENCH_obs_*.json is
#            committed the observability child (scripts/bench_obs.py)
#            runs too and its obs_overhead_frac (lower-is-better, <=3%
#            budget) / obs_trace_linked / obs_metrics_valid keys merge
#            into the same baseline
#   data     the streaming packed data plane A/B (mix -> pack_stream vs
#            pad-to-max on the pinned ragged corpus) vs the last committed
#            BENCH_data_*.json — packed tokens/s speedup, padding waste
#            reclaimed, 0 mid-run recompiles, data_wait_s (lower-is-better)
#   tier1    (opt-in: --suite tier1; NOT part of --suite all, CI runs the
#            test suite separately) the tier-1 pytest suite wall time vs
#            the last committed BENCH_tier1_*.json — tier1_suite_wall_s
#            lower-is-better, tier1_exit_ok pass/fail
#
# Baselines recorded on a DIFFERENT host print a WARNING naming the
# absolute keys (_per_sec/_s) whose floors may not transfer; ratio keys
# are compared regardless.
#
# Runs after the lint gate in the CI flow:
#
#     scripts/lint_gate.sh && scripts/perf_gate.sh
#
# Usage: scripts/perf_gate.sh [extra gate args, e.g. --suite serve
#        --tolerance 0.2 --baseline BENCH_kernels_pr06.json --current f.json]
# With no args ALL suites run (each measures fresh in a CPU-pinned child —
# a few minutes); exit 0 pass, 1 regression, 2 could-not-measure.
set -euo pipefail
cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    exec env JAX_PLATFORMS=cpu python bench.py --gate "$@"
fi
exec env JAX_PLATFORMS=cpu python bench.py --gate --suite all
