"""Bench receipt for the linter itself: lint_wall_s of a full self-lint
run (dmlcloud_tpu/ + examples/ + bench.py + scripts/), serial vs --jobs.

The lint gate runs on every CI invocation and every pre-commit hook — its
cost is part of the perf trajectory like any hot path, so it gets a
receipt (BENCH_lint_pr05.json) the same way compile/overlap wins do.

    python scripts/bench_lint.py [-o BENCH_lint_pr05.json] [--jobs N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dmlcloud_tpu.lint import lint_paths  # noqa: E402
from dmlcloud_tpu.lint.engine import iter_python_files  # noqa: E402

TARGETS = ["dmlcloud_tpu", "examples", "bench.py", "scripts"]


def _time_lint(paths, jobs: int, repeats: int = 3) -> tuple[float, int]:
    """Best-of-N wall seconds (best-of filters scheduler noise the same way
    bench.py's step timers do) and the finding count of the last run."""
    best = float("inf")
    findings = 0
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = lint_paths(paths, jobs=jobs)
        best = min(best, time.perf_counter() - t0)
        findings = len(result)
    return best, findings


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("-o", "--output", default=os.path.join(REPO, "BENCH_lint_pr05.json"))
    parser.add_argument("--jobs", type=int, default=max(2, min(os.cpu_count() or 2, 8)))
    parser.add_argument("--repeats", type=int, default=3)
    args = parser.parse_args(argv)

    paths = [os.path.join(REPO, t) for t in TARGETS]
    files = sum(1 for _ in iter_python_files(paths))
    serial_s, findings = _time_lint(paths, jobs=1, repeats=args.repeats)
    jobs_s, _ = _time_lint(paths, jobs=args.jobs, repeats=args.repeats)

    receipt = {
        "bench": "lint_selflint",
        "targets": TARGETS,
        "files_scanned": files,
        "findings": findings,
        "repeats_best_of": args.repeats,
        "lint_wall_s": round(serial_s, 4),
        "lint_wall_s_jobs": round(jobs_s, 4),
        "jobs": args.jobs,
        "speedup": round(serial_s / jobs_s, 3) if jobs_s > 0 else None,
        "rules": "DML1xx + DML2xx + DML3xx (flow-aware engine, project axis registry)",
    }
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(receipt, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(receipt, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
