"""Bench receipt for the linter's incremental cache: cold vs warm wall
seconds of a full self-lint (dmlcloud_tpu/ + examples/ + bench.py +
scripts/, whole-program DML5xx pass included).

The lint gate runs on every CI invocation and every pre-commit hook — its
cost is part of the perf trajectory like any hot path. PR 5's receipt
(BENCH_lint_pr05.json) recorded the serial-vs-jobs split; this one records
the cache split: a warm run (nothing changed, everything replays from
``.dmllint_cache.json``-style state) must finish in at most
``WARM_BUDGET_FRAC`` of the cold run, and must produce byte-identical
findings — a cache that changes the answer is a bug, not a perf number.
``bench.py --gate --suite lint`` enforces both against the committed
receipt (missing metric = FAIL).

    python scripts/bench_lint.py [-o BENCH_lint_pr17.json] [--repeats N]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dmlcloud_tpu.lint import lint_paths  # noqa: E402
from dmlcloud_tpu.lint.engine import iter_python_files  # noqa: E402

TARGETS = ["dmlcloud_tpu", "examples", "bench.py", "scripts"]

#: a warm (fully cached) run must cost at most this fraction of cold
WARM_BUDGET_FRAC = 0.35


def measure(repeats: int = 3) -> dict | None:
    """Best-of-N cold and warm wall seconds over the self-lint targets.
    Returns the receipt dict, or None if the warm run ever disagreed with
    the cold run's findings (correctness before speed)."""
    paths = [os.path.join(REPO, t) for t in TARGETS if os.path.exists(os.path.join(REPO, t))]
    files = sum(1 for _ in iter_python_files(paths))
    cold_best = warm_best = float("inf")
    findings = 0
    with tempfile.TemporaryDirectory() as tmp:
        for i in range(repeats):
            cache = os.path.join(tmp, f"cache{i}.json")
            t0 = time.perf_counter()
            cold = lint_paths(paths, cache=cache)
            cold_best = min(cold_best, time.perf_counter() - t0)
            t0 = time.perf_counter()
            warm = lint_paths(paths, cache=cache)
            warm_best = min(warm_best, time.perf_counter() - t0)
            if warm != cold:
                return None
            findings = len(cold)
    return {
        "bench": "lint_incremental",
        "value_source": "cpu_smoke",
        "host": {
            "cpu_count": os.cpu_count(),
            "platform": platform.platform(),
            "python": platform.python_version(),
        },
        "targets": TARGETS,
        "files_scanned": files,
        "findings": findings,
        "repeats_best_of": repeats,
        "warm_budget_frac": WARM_BUDGET_FRAC,
        "gate": {
            "lint_cold_wall_s": round(cold_best, 4),
            "lint_warm_wall_s": round(warm_best, 4),
            "lint_incremental_ok": int(warm_best <= WARM_BUDGET_FRAC * cold_best),
        },
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("-o", "--output", default=os.path.join(REPO, "BENCH_lint_pr17.json"))
    parser.add_argument("--repeats", type=int, default=3)
    args = parser.parse_args(argv)

    receipt = measure(repeats=args.repeats)
    if receipt is None:
        print("bench_lint: FAIL — warm run disagreed with cold run", file=sys.stderr)
        return 1
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(receipt, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(receipt, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
