#!/usr/bin/env python
"""Data-plane A/B receipt: pad-to-max vs the streaming packed input path
(``DataPipeline.mix -> pack_stream -> batch``) vs the DISK-NATIVE path
(``ShardReader -> pack_stream(pack_window=...)``) on the pinned ragged
corpus (doc/data.md):

- real (non-padding) tokens/s through the SAME TrainValStage train step
  for all three arms — the pad arm burns ~3/4 of every batch on padding,
  the packed arm reclaims it, and the disk arm reads the same documents
  COLD from a temp ``.dmlshard`` corpus through the async mmap reader
  while the window-FFD packer cuts pad_fraction under 1%
- padding-waste fraction per arm, with the boundary share reported
  separately (chunk tails for greedy; end-of-stream flush only for FFD)
- data_wait_s from the telemetry ledger, 0 mid-run recompiles (packed
  rows are fixed-shape by construction; AOT-precompiled signature), and
  the reshard replay drill: a 4-reader cursor saved mid-corpus and
  resumed by 2 readers must cover every record exactly once
  (``data_disk_zero_replay``)

Thin CLI over ``bench.bench_data`` (which runs ``bench.py --data-child``
CPU-pinned) so the committed receipt and an interactive investigation run
the exact same workload. The receipt's flat ``gate`` section is what
``bench.py --gate --suite data`` / scripts/perf_gate.sh compares.

    JAX_PLATFORMS=cpu python scripts/bench_data.py --out BENCH_data_pr18.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None, help="also write the receipt JSON here")
    args = parser.parse_args()

    from bench import bench_data

    results = bench_data()
    if results is None:
        print("data bench failed (child produced no results)", file=sys.stderr)
        return 1
    payload = json.dumps(results, indent=2)
    print(payload)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
