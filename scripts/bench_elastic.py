#!/usr/bin/env python
"""Elastic-resume receipt: the preemption drill as a benchmark
(doc/elasticity.md). Trains on 4 fake CPU devices, delivers a REAL SIGTERM
mid-epoch, drains at the next step-save boundary, resumes the SAME run dir
on 2 devices, and reports:

- ``save_on_preempt_latency_s``  the drain's final committed save
- ``time_to_resume_s``           resume start -> first resumed step
- ``steps_replayed``             0 on exact data-order resumption

Thin CLI over ``bench.bench_elastic`` (which runs ``bench.py
--elastic-child`` pinned to 4 CPU devices) so the committed receipt and an
interactive investigation run the exact same drill. The receipt's flat
``gate`` section is what ``bench.py --gate --suite elastic`` /
scripts/perf_gate.sh compares.

    JAX_PLATFORMS=cpu python scripts/bench_elastic.py --out BENCH_elastic_pr07.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=None, help="also write the receipt JSON here")
    args = parser.parse_args()

    from bench import bench_elastic

    results = bench_elastic()
    if results is None:
        print("elastic drill failed (child produced no results)", file=sys.stderr)
        return 1
    payload = json.dumps(results, indent=2)
    print(payload)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
