"""Roofline breakdown of a ``jax.profiler`` trace, by HLO category.

Thin CLI over ``dmlcloud_tpu.utils.profiling.roofline`` (which parses the
xplane.pb's own per-op counters — the same data XProf's op-profile tab
renders). This is how doc/performance.md §5's ResNet ledger was produced:

    python scripts/tune_resnet.py --trace /tmp/tr
    python scripts/analyze_trace.py /tmp/tr --steps 30

Notes on the counters (they are the chip's own accounting, not estimates):
- ``flops`` counts a multiply-add as TWO ops — the MFU convention. This is
  how the 16%-MFU myth for the ResNet bench died: the widely quoted
  "4.1 GFLOPs" for ResNet-50 is a MAC count, and the hardware executes
  2x that, which the trace shows directly (23.9 GFLOPs/image trained).
- ``bytes_accessed`` includes VMEM-resident operand reads, so the aggregate
  can exceed the HBM peak; per-op numbers near the HBM peak still identify
  bandwidth-bound ops (their operands stream from HBM).

Requires tensorflow (baked into this image) for the xplane proto only.
"""

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from dmlcloud_tpu.utils.profiling import format_roofline, roofline

#: bump when the --json object's shape changes (consumers pin on this)
JSON_SCHEMA_VERSION = 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_dir", help="directory passed to jax.profiler.trace")
    ap.add_argument("--steps", type=int, default=30, help="timed steps inside the trace")
    ap.add_argument(
        "--json", action="store_true",
        help='machine-readable output: {"version", "steps", "peaks", "rows"}',
    )
    args = ap.parse_args(argv)
    peaks, rows = roofline(args.trace_dir, steps=args.steps)
    if not rows:
        # a device plane with zero op events: the traced region dispatched no
        # device work (trace() wrapped host-only code, or the steps never ran)
        print(
            f"analyze_trace: trace under {args.trace_dir} contains no XLA op rows — "
            "the traced region executed no device work. Wrap actual train steps "
            "in profiling.trace() and block_until_ready before closing it.",
            file=sys.stderr,
        )
        return 1
    if args.json:
        print(
            json.dumps(
                {
                    "version": JSON_SCHEMA_VERSION,
                    "steps": args.steps,
                    "peaks": peaks,
                    "rows": rows,
                },
                sort_keys=True,
            )
        )
    else:
        print(format_roofline(peaks, rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
