"""Roofline breakdown of a ``jax.profiler`` trace, by HLO category —
or, pointed at a SERVE run's span journals, the per-request latency table.

Thin CLI over ``dmlcloud_tpu.utils.profiling.roofline`` (which parses the
xplane.pb's own per-op counters — the same data XProf's op-profile tab
renders). This is how doc/performance.md §5's ResNet ledger was produced:

    python scripts/tune_resnet.py --trace /tmp/tr
    python scripts/analyze_trace.py /tmp/tr --steps 30

Notes on the counters (they are the chip's own accounting, not estimates):
- ``flops`` counts a multiply-add as TWO ops — the MFU convention. This is
  how the 16%-MFU myth for the ResNet bench died: the widely quoted
  "4.1 GFLOPs" for ResNet-50 is a MAC count, and the hardware executes
  2x that, which the trace shows directly (23.9 GFLOPs/image trained).
- ``bytes_accessed`` includes VMEM-resident operand reads, so the aggregate
  can exceed the HBM peak; per-op numbers near the HBM peak still identify
  bandwidth-bound ops (their operands stream from HBM).

When the directory holds telemetry span journals instead (a serve run:
``journal-rank*.jsonl`` under it or its ``telemetry/``), the analysis
switches to the request plane — per-request TTFT/ITL percentiles derived
from the linked traces (doc/observability.md), with ``--tenant`` focusing
one tenant's requests. ITL is estimated from the gaps between successive
decode batches a request rode (the journal records batches, not tokens).

Requires tensorflow (baked into this image) for the xplane proto only —
the serve path is pure stdlib + numpy.
"""

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from dmlcloud_tpu.utils.profiling import format_roofline, roofline  # noqa: E402

#: bump when the --json object's shape changes (consumers pin on this).
#: v2 is ADDITIVE over v1: the roofline keys ("steps"/"peaks"/"rows")
#: are unchanged; serve-journal inputs add a "serve" object instead.
JSON_SCHEMA_VERSION = 2

_BATCH_KINDS = ("decode_batch", "draft", "verify", "medusa")


def _pcts(vals):
    import numpy as np

    if not vals:
        return {"n": 0, "p50": None, "p90": None, "p99": None}
    return {
        "n": len(vals),
        "p50": round(float(np.percentile(vals, 50)), 3),
        "p90": round(float(np.percentile(vals, 90)), 3),
        "p99": round(float(np.percentile(vals, 99)), 3),
    }


def serve_summary(records, tenant=None):
    """Per-request latency scorecard from journal records: TTFT per trace
    (arrival -> end of its last prefill chunk, the step that samples the
    first token), ITL per trace (gaps between the ENDS of successive
    batch spans it rode), grouped overall and per tenant. ``tenant``
    narrows to one tenant's traces (requests with no tenant attr carry
    ``""``)."""
    from dmlcloud_tpu.telemetry.journal import linked_trace_report

    report = linked_trace_report(records)
    ttfts, itls = [], []
    tenants = {}
    kept = 0
    for tid, spans in report["traces"].items():
        ten = next(
            (str(s["tenant"]) for s in spans if s.get("tenant") not in (None,)),
            "",
        )
        if tenant is not None and ten != tenant:
            continue
        kept += 1
        t0 = min(s["ts"] for s in spans)
        prefills = [s for s in spans if s["kind"] == "prefill"]
        entry = tenants.setdefault(ten, {"ttft": [], "itl": []})
        if prefills:
            ttft_ms = (max(s["ts"] + s["dur"] for s in prefills) - t0) * 1e3
            ttfts.append(ttft_ms)
            entry["ttft"].append(ttft_ms)
        ends = sorted(
            s["ts"] + s["dur"] for s in spans if s["kind"] in _BATCH_KINDS
        )
        gaps = [(b - a) * 1e3 for a, b in zip(ends, ends[1:])]
        itls.extend(gaps)
        entry["itl"].extend(gaps)
    statuses = {}
    for tid, st in report["statuses"].items():
        key = st if st is not None else "ok"
        statuses[key] = statuses.get(key, 0) + 1
    return {
        "requests": kept,
        "spans": len(records),
        "orphan_spans": len(report["orphans"]),
        "statuses": statuses,
        "ttft_ms": _pcts(ttfts),
        "itl_ms": _pcts(itls),
        "tenants": {
            t: {"ttft_ms": _pcts(v["ttft"]), "itl_ms": _pcts(v["itl"])}
            for t, v in sorted(tenants.items())
        },
    }


def _format_serve(s):
    def row(name, p):
        f = lambda v: "      -" if v is None else f"{v:7.1f}"  # noqa: E731
        return f"  {name:<10} {p['n']:>5} {f(p['p50'])} {f(p['p90'])} {f(p['p99'])}"

    lines = [
        f"serve journal: {s['requests']} requests, {s['spans']} spans "
        f"({s['orphan_spans']} orphans), statuses {s['statuses']}",
        f"  {'':<10} {'n':>5} {'p50':>7} {'p90':>7} {'p99':>7}",
        row("ttft_ms", s["ttft_ms"]),
        row("itl_ms", s["itl_ms"]),
    ]
    for t, v in s["tenants"].items():
        lines.append(f"  tenant {t or '(default)'!r}:")
        lines.append(row("  ttft_ms", v["ttft_ms"]))
        lines.append(row("  itl_ms", v["itl_ms"]))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "trace_dir",
        help="directory passed to jax.profiler.trace, or a serve run dir "
        "with telemetry journals",
    )
    ap.add_argument("--steps", type=int, default=30, help="timed steps inside the trace")
    ap.add_argument(
        "--tenant", default=None,
        help="serve journals: only this tenant's requests",
    )
    ap.add_argument(
        "--json", action="store_true",
        help='machine-readable output: {"version", "steps", "peaks", "rows"} '
        'for a profiler trace, {"version", "serve"} for serve journals',
    )
    args = ap.parse_args(argv)

    # serve-journal mode: span journals under the dir win over xplane
    from dmlcloud_tpu.telemetry.journal import load_journals

    try:
        records = load_journals(args.trace_dir)
    except FileNotFoundError:
        records = []
    if records:
        summary = serve_summary(records, tenant=args.tenant)
        if args.json:
            print(json.dumps({"version": JSON_SCHEMA_VERSION, "serve": summary},
                             sort_keys=True))
        else:
            print(_format_serve(summary))
        return 0
    if args.tenant is not None:
        print("analyze_trace: --tenant only applies to serve journals",
              file=sys.stderr)
        return 2

    peaks, rows = roofline(args.trace_dir, steps=args.steps)
    if not rows:
        # a device plane with zero op events: the traced region dispatched no
        # device work (trace() wrapped host-only code, or the steps never ran)
        print(
            f"analyze_trace: trace under {args.trace_dir} contains no XLA op rows — "
            "the traced region executed no device work. Wrap actual train steps "
            "in profiling.trace() and block_until_ready before closing it.",
            file=sys.stderr,
        )
        return 1
    if args.json:
        print(
            json.dumps(
                {
                    "version": JSON_SCHEMA_VERSION,
                    "steps": args.steps,
                    "peaks": peaks,
                    "rows": rows,
                },
                sort_keys=True,
            )
        )
    else:
        print(format_roofline(peaks, rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
