"""Roofline breakdown of a ``jax.profiler`` trace, by HLO category.

Parses the xplane.pb a trace directory contains (the same data XProf's
op-profile tab renders) and prints, per HLO category: share of device time,
achieved TFLOP/s, and achieved GB/s — next to the chip's hardware peaks,
which the xplane also records. This is how doc/performance.md §5's ResNet
ledger was produced:

    python scripts/tune_resnet.py --trace /tmp/tr
    python scripts/analyze_trace.py /tmp/tr --steps 30

Notes on the counters (they are the chip's own accounting, not estimates):
- ``flops`` counts a multiply-add as TWO ops — the MFU convention. This is
  how the 16%-MFU myth for the ResNet bench died: the widely quoted
  "4.1 GFLOPs" for ResNet-50 is a MAC count, and the hardware executes
  2x that, which the trace shows directly (23.9 GFLOPs/image trained).
- ``bytes_accessed`` includes VMEM-resident operand reads, so the aggregate
  can exceed the HBM peak; per-op numbers near the HBM peak still identify
  bandwidth-bound ops (their operands stream from HBM).

Requires tensorflow (baked into this image) for the xplane proto only.
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import sys

# the generated protos predate protobuf 5's C++ descriptor pool checks
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def load_xspace(trace_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb"))
    if not paths:
        sys.exit(f"no xplane.pb under {trace_dir} (is this a jax.profiler trace dir?)")
    xs = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def device_plane(xs):
    for p in xs.planes:
        if p.name.startswith("/device:TPU") and any(l.name == "XLA Ops" for l in p.lines):
            return p
    sys.exit("no TPU device plane with an 'XLA Ops' line in this trace")


def _stat_value(plane, st):
    """Decode an XStat across its value oneof (incl. uint64 and interned refs)."""
    kind = st.WhichOneof("value")
    if kind is None:
        return None
    if kind == "ref_value":  # string interned in stat_metadata
        return plane.stat_metadata[st.ref_value].name
    return getattr(st, kind)


def plane_stats(plane) -> dict:
    return {
        plane.stat_metadata[st.metadata_id].name: _stat_value(plane, st) for st in plane.stats
    }


def analyze(trace_dir: str, steps: int):
    plane = device_plane(load_xspace(trace_dir))
    peaks = plane_stats(plane)
    peak_tf = float(peaks.get("peak_teraflops_per_second", 0) or 0)
    peak_hbm = float(peaks.get("peak_hbm_bw_gigabytes_per_second", 0) or 0)

    def md_stats(md):
        return {
            plane.stat_metadata[st.metadata_id].name: _stat_value(plane, st) for st in md.stats
        }

    (ops_line,) = [l for l in plane.lines if l.name == "XLA Ops"]
    agg = collections.defaultdict(lambda: [0.0, 0.0, 0.0, 0])  # ps, flops, bytes, n
    for ev in ops_line.events:
        s = md_stats(plane.event_metadata[ev.metadata_id])
        row = agg[s.get("hlo_category", "?")]
        row[0] += ev.duration_ps
        row[1] += float(s.get("flops", 0) or 0)
        row[2] += float(s.get("bytes_accessed", 0) or 0)
        row[3] += 1

    total_ps = sum(v[0] for v in agg.values())
    total_fl = sum(v[1] for v in agg.values())
    total_by = sum(v[2] for v in agg.values())
    print(
        f"device: {peaks.get('device_type_string', '?')}  "
        f"peak {peak_tf:.0f} TF/s, HBM {peak_hbm:.0f} GB/s"
    )
    print(f"{'category':<28}{'time%':>7}{'ms/step':>9}{'TFLOP/s':>9}{'GB/s':>8}{'n/step':>8}")
    for cat, (ps, fl, by, n) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        if ps / total_ps < 0.001:
            continue
        print(
            f"{cat:<28}{ps / total_ps * 100:>6.1f}%{ps / 1e9 / steps:>8.2f}"
            # flops are counted over the events' own duration: flops/ps == TFLOP/s
            f"{fl / ps if ps else 0:>9.1f}{by / (ps / 1e12) / 1e9 if ps else 0:>8.0f}"
            f"{n // steps:>8}"
        )
    pct_peak = f" ({total_fl / total_ps / peak_tf * 100:.0f}% of peak)" if peak_tf else ""
    print(
        f"\ntotal: {total_ps / 1e9 / steps:.2f} ms/step on device; aggregate "
        f"{total_fl / total_ps:.1f} TFLOP/s{pct_peak}, "
        f"{total_by / (total_ps / 1e12) / 1e9:.0f} GB/s nominal"
    )


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_dir", help="directory passed to jax.profiler.trace")
    ap.add_argument("--steps", type=int, default=30, help="timed steps inside the trace")
    args = ap.parse_args()
    analyze(args.trace_dir, args.steps)


if __name__ == "__main__":
    main()
