#!/usr/bin/env bash
# CI lint gate: the whole framework, the examples, the bench harness, and
# the scripts must satisfy the contracts the linter enforces (doc/lint.md).
# --format=github makes each finding an inline PR annotation on GitHub
# Actions; locally the same command prints ::error lines and exits 1.
#
# Usage: scripts/lint_gate.sh [--changed] [extra lint args, e.g. --jobs 4]
#   --changed   incremental mode: enables the lint cache (.dmllint_cache.json)
#               so only files that changed since the last run — plus their
#               transitive reverse importers — are re-analyzed. Findings are
#               identical to a cold run (the cache is advisory); use it for
#               pre-commit hooks and local iteration, keep CI cold.
# CI runs this first, then the perf regression gate:
#     scripts/lint_gate.sh && scripts/perf_gate.sh
set -euo pipefail
cd "$(dirname "$0")/.."
args=()
for a in "$@"; do
  if [ "$a" = "--changed" ]; then
    args+=("--cache")
  else
    args+=("$a")
  fi
done
exec python -m dmlcloud_tpu lint dmlcloud_tpu examples bench.py scripts --format=github "${args[@]+"${args[@]}"}"
