#!/usr/bin/env bash
# CI lint gate: the whole framework, the examples, the bench harness, and
# the scripts must satisfy the contracts the linter enforces (doc/lint.md).
# --format=github makes each finding an inline PR annotation on GitHub
# Actions; locally the same command prints ::error lines and exits 1.
#
# Usage: scripts/lint_gate.sh [extra lint args, e.g. --jobs 4]
# CI runs this first, then the perf regression gate:
#     scripts/lint_gate.sh && scripts/perf_gate.sh
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m dmlcloud_tpu lint dmlcloud_tpu examples bench.py scripts --format=github "$@"
