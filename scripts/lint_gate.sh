#!/usr/bin/env bash
# CI lint gate: the whole framework, the examples, the bench harness, and
# the scripts must satisfy the contracts the linter enforces (doc/lint.md).
# --format=github makes each finding an inline PR annotation on GitHub
# Actions; locally the same command prints ::error lines and exits 1.
#
# The PR-17 incremental cache is ALWAYS on (--cache): warm runs re-analyze
# only files that changed since the last run plus their transitive reverse
# importers — the measured 0.02x path (BENCH_lint receipts) — with findings
# identical to a cold run (the cache is advisory, it can only be slow, not
# wrong). Where git metadata exists the gate also passes --changed, so a
# warm run at an unchanged HEAD skips even the per-file content re-hash.
#
# Usage: scripts/lint_gate.sh [--cold] [extra lint args, e.g. --jobs 4]
#   --cold   drop the cache first and run without it (use when bisecting a
#            suspected cache bug; findings are identical either way)
# CI runs this first, then the perf regression gate:
#     scripts/lint_gate.sh && scripts/perf_gate.sh
set -euo pipefail
cd "$(dirname "$0")/.."
args=()
cold=0
for a in "$@"; do
  if [ "$a" = "--cold" ]; then
    cold=1
  else
    args+=("$a")
  fi
done
if [ "$cold" = 1 ]; then
  rm -f .dmllint_cache.json
else
  args+=("--cache")
  if git rev-parse --git-dir >/dev/null 2>&1; then
    args+=("--changed")
  fi
fi
exec python -m dmlcloud_tpu lint dmlcloud_tpu examples bench.py scripts --format=github "${args[@]+"${args[@]}"}"
